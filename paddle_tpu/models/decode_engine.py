"""Decode engine: ONE home for every decode capability.

Reference counterpart: tests/unittests/dist_transformer.py:1498
fast_decode is the decode loop all of this re-designs TPU-first; the
slot-pool/paged serving discipline follows Orca (OSDI'22), vLLM
(SOSP'23 — PagedAttention block tables) and SGLang (RadixAttention
prefix sharing), PAPERS.md.

models/transformer.py used to carry three decode builders (whole-loop,
incremental, DecodeStepBundle) with ~600 lines of overlapping loop/
cache/emission logic, so every new decode capability (paged KV,
speculative, sampling, sharding) had to be implemented three times.
This module factors the decode machinery into composable pieces the
builders share — transformer.py's builder entry points keep their
public signatures and delegate here:

* **Cache layout** — ``CacheConfig`` selects ``dense`` (per-lane
  ``[rows, H, maxT, Dh]`` KV buffers, the r10 design) or ``paged``
  (a SHARED block pool ``[n_blocks, block_size, H, Dh]`` per layer +
  per-lane int32 block-table rows; cross-attention K/V lives in a
  refcounted prompt-entry pool so identical prompts prefill ONCE).
  Reads go through one-hot/gather composition of existing ops; writes
  go through the ``masked_pool_write`` registry op whose disjoint
  one-hot masks are the lane-exclusivity contract checker PTA110
  enforces (shared-pool aliasing is the silent cross-request KV
  corruption class).
* **Step body** — ``cached_decoder_step`` runs the KV-cached decoder
  stack over per-layer cache-access objects (``_DenseLaneCache`` /
  ``_PagedLaneCache``), so the whole-loop, single-step and paged
  programs trace IDENTICAL math — token-for-token parity across
  layouts is structural, not coincidental.
* **Loop/burst/exit policy** — the serve-program While (n_steps +
  min_active early exits) and the scalar-counter whole-loop tail.
* **Emission** — the greedy emit/EOS-freeze/one-hot-write tail, in
  scalar-loop and per-lane-vectorized forms.

Host-side allocation policy (``HostBlockPool``, ``PromptPrefixCache``)
also lives here: the device only ever sees fed/persistable tables, so
blocks/refcounts/prefix hashing stay plain testable Python in the
serving scheduler (inference/serving.py).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import layers
from ..analysis import absint
from ..observability import devtel
from ..observability.devtel import DECODE_STEPS_VAR  # noqa: F401
from ..param_attr import ParamAttr

# name mark on SHARED block-pool persistables: checker PTA110 requires
# every write to a var carrying this mark to be a provably
# lane-exclusive masked_pool_write (analysis/checkers.py)
POOL_MARK = "@POOL"

# the mesh-axis name that WOULD shard decode lanes across devices.
# No shipped lowering shards lanes (tensor parallelism shards heads;
# data parallelism is replica servers on disjoint device slices), so
# a tp-only mesh proves the serve While's burst-exit predicate
# uniform — but the burst-exit mark names this axis so that any
# future lane-sharding mesh flips the prover back to
# proven-divergent automatically (absint.mark_divergence_source
# axes= semantics).
LANE_AXIS = "lanes"


@dataclass(frozen=True)
class ShardingConfig:
    """Tensor-parallel execution layout of a decode bundle — the
    Megatron-LM composition (Shoeybi et al.; SNIPPETS.md [1]/[3]'s
    ``Mesh + NamedSharding`` pattern) re-designed for the decode
    engine's serving regime:

    * self/cross KV state sharded along HEADS — dense per-lane
      buffers ``[R, H/tp, T, Dh]``, the paged pools
      ``[n_blocks, block_size, H/tp, Dh]`` — so per-device KV bytes
      drop ~1/tp. Block tables / prompt refs stay host-owned and
      REPLICATED: ``HostBlockPool`` and the PTA190/191 ownership
      proofs are untouched.
    * column/row-parallel ffn (fc1 out-dim, fc2 in-dim), row-parallel
      attention out-projections, column-parallel cross-attention
      query, vocab-sharded logits head. The implied psums/allgathers
      sit inside the decode-burst While — legal under GSPMD exactly
      because the burst-exit predicate is PROVEN value-uniform on a
      tp-only mesh (PTA130/131/160/161; the r5 contract).
    * the CONTIGUOUS fused self-attention qkv projection and the fused
      cross-KV projection stay REPLICATED deliberately: their
      ``split`` on the fused output axis crosses tp shard boundaries,
      so column-sharding them would force a reshard collective EVERY
      tick — PTA160 rejects that shape inside the While.
      ``qkv_interleaved=True`` switches the decode-step builders to
      the HEAD-INTERLEAVED fused layout (``dec{li}_self_qkvh.w``,
      columns ordered ``[H, 3, Dh]``-major): the q/k/v decomposition
      becomes reshape ``[.., H, 3, Dh]`` → split on the local 3-axis →
      squeeze → transpose, every step of which carries a head-sharded
      placement locally (analysis/sharding_rules.py reshape
      major-carry + split/squeeze/transpose rules), so the qkv weight
      column-shards with ZERO per-tick reshard — the Megatron
      column-parallel attention block, completed. Convert trained
      contiguous weights with ``interleave_qkv_params``.

    ``dp`` replica lanes are NOT part of this config: data
    parallelism is separate server instances on disjoint device
    slices (inference/runtime/placement.py), each carrying its own
    bound copy of this plan.

    Reference counterpart: reference
    transpiler/distribute_transpiler.py:69 VarBlock sliced params by
    REWRITING programs at runtime; a declarative layout config the
    compiler partitions from is the GSPMD-era shape.
    """

    tp: int = 1
    axis: str = "tp"
    # head-interleaved fused-qkv weight layout (dec{li}_self_qkvh.w)
    # — lets the fused qkv projection column-shard under tp; False
    # keeps the contiguous (replicated-qkv) layout byte-compatible
    # with pre-r19 checkpoints
    qkv_interleaved: bool = False

    @property
    def enabled(self) -> bool:
        return self.tp > 1

    def validate(self, n_heads: int, vocab: int, d_model: int,
                 d_inner: int):
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if not self.enabled:
            return
        for what, dim in (("n_heads", n_heads), ("vocab", vocab),
                          ("d_model", d_model), ("d_inner", d_inner)):
            if dim % self.tp:
                raise ValueError(
                    f"ShardingConfig(tp={self.tp}) needs {what} "
                    f"divisible by tp, got {what}={dim}")
        if self.axis == LANE_AXIS:
            raise ValueError(
                f"mesh axis {LANE_AXIS!r} is reserved for (future) "
                f"lane sharding — the serve While's divergence mark "
                f"names it; pick another tp axis name")

    def token(self) -> tuple:
        return ("tp", int(self.tp), self.axis,
                int(self.qkv_interleaved))


@dataclass(frozen=True)
class CacheConfig:
    """KV cache layout of a slot-pool decode bundle.

    ``dense``: per-lane KV buffers — every admitted request reserves
    the full ``[maxT, ...]`` self-KV and ``[seq_len, ...]`` cross-KV
    regardless of its actual generation/prompt reuse (the r10 layout).

    ``paged``: self-attention KV lives in ONE shared pool of
    ``n_blocks`` blocks of ``block_size`` positions per layer
    (``[n_blocks, block_size, n_heads, head_dim]``), addressed through
    per-lane int32 block-table rows the HOST allocates
    (``HostBlockPool``); cross-attention K/V lives in a pool of
    ``n_prompt_entries`` whole-prompt entries (+1 dustbin), shared
    refcounted across lanes with identical prompts
    (``PromptPrefixCache``) so a repeated system prompt prefills once
    and later admissions skip the encoder entirely.
    """

    layout: str = "dense"          # "dense" | "paged"
    block_size: int = 8            # positions per self-KV block
    n_blocks: int = 0              # shared self-KV pool blocks
    n_prompt_entries: int = 0      # shared cross-KV prompt entries
    chunk_tokens: int = 0          # >0: build ("chunked", p) prefill
    #                                phase programs processing this
    #                                many prompt tokens per tick

    def validate(self, max_out_len: int):
        if self.layout not in ("dense", "paged"):
            raise ValueError(f"unknown KV layout {self.layout!r}")
        if self.layout == "paged":
            if self.block_size < 1 or self.n_blocks < 1 \
                    or self.n_prompt_entries < 1:
                raise ValueError(
                    f"paged layout needs block_size/n_blocks/"
                    f"n_prompt_entries >= 1, got {self}")
            if max_out_len % self.block_size != 0:
                raise ValueError(
                    f"block_size={self.block_size} must divide "
                    f"max_out_len={max_out_len} (token-exact parity "
                    f"needs the paged cache view to cover exactly the "
                    f"dense [maxT] positions)")
        if self.chunk_tokens < 0:
            raise ValueError(
                f"chunk_tokens must be >= 0, got {self.chunk_tokens}")
        if self.chunk_tokens and self.layout != "paged":
            raise ValueError(
                "chunked prefill needs the paged layout (chunks land "
                "in the shared prompt-entry pool)")
        if self.chunk_tokens == 1:
            raise ValueError(
                "chunk_tokens == 1 is rejected: a single-query "
                "attention chunk lowers to a different XLA "
                "contraction whose accumulation order drifts ~1e-7 "
                "from the monolithic encoder, breaking the bit-exact "
                "chunked==monolithic parity contract (any C >= 2 is "
                "exact — the ragged last chunk keeps width C by "
                "zero-padding, so no dispatch ever sees a "
                "single-query shape)")

    @property
    def chunked(self) -> bool:
        return self.layout == "paged" and self.chunk_tokens > 0

    def n_chunks(self, seq_len: int) -> int:
        """Ticks needed to stream one seq_len prompt through at
        chunk_tokens per tick (ceil division; the last chunk may be
        ragged — phase bodies mask past-the-end positions)."""
        c = self.chunk_tokens
        return (seq_len + c - 1) // c if c else 0

    def pages(self, max_out_len: int) -> int:
        return max_out_len // self.block_size

    def token(self) -> tuple:
        """Content identity of the layout — part of
        ``server_fingerprint`` and therefore of hot-swap/dedupe
        decisions: two servers differing only in KV layout must not
        dedupe as 'same fingerprint' (inference/runtime/registry.py)."""
        if self.layout == "dense":
            return ("dense",)
        tok = ("paged", self.block_size, self.n_blocks,
               self.n_prompt_entries)
        # append-only so historical paged tokens stay byte-identical:
        # a chunked and an unchunked build of one geometry carry
        # different program sets and must never dedupe
        if self.chunk_tokens:
            tok = tok + ("chunk", self.chunk_tokens)
        return tok

    @staticmethod
    def suggest_chunk_tokens(bundle, tick_budget_ms: float,
                             prefill_ms: float = 150.0) -> int:
        """Largest power-of-two chunk size whose per-tick prefill
        slice fits ``tick_budget_ms`` — the PERF.md "Chunk-size
        arithmetic" made callable (the PR 17 leftover ROADMAP named:
        tuning C per shape was manual).

        One chunk tick runs ONE phase over C prompt tokens; a
        monolithic prefill runs all ``2L+2`` phases over all
        ``seq_len`` tokens in ``prefill_ms`` (default: the measured
        ~150 ms for the 2k-token encoder on the throttled CPU host —
        pass a fresh measurement for other shapes/backends). So
        ``tick(C) ~= prefill_ms * C / (seq_len * n_phases)``, and the
        two-tier schedule bounds every decode tick's wait by one such
        slice. L is read off the bundle's state specs (one cross_k
        entry per layer); the floor is C=2 because ``validate``
        rejects C=1 (accumulation-order drift breaks byte-exact
        parity). Worked example (PERF.md): seq_len=2048, L=1 (4
        phases), 5.0 ms budget -> C=256 (tick 4.69 ms; C=512 would
        be 9.38 ms).

        Reference counterpart: none — the reference has no chunked
        prefill; DistServe-style chunk sizing is serving-era
        arithmetic."""
        if tick_budget_ms <= 0:
            raise ValueError(
                f"tick_budget_ms must be > 0, got {tick_budget_ms}")
        seq_len = int(bundle.seq_len)
        n_layers = sum(1 for name in bundle._state_specs
                       if "cross_k" in name)
        n_phases = 2 * max(n_layers, 1) + 2

        def tick(c):
            return prefill_ms * c / (seq_len * n_phases)

        c = 2
        while c * 2 <= seq_len and tick(c * 2) <= tick_budget_ms:
            c *= 2
        return c


# ---------------------------------------------------------------------------
# Emission helpers (shared by every decode front).
# ---------------------------------------------------------------------------
def step_logits(dec, positions, counter, vocab):
    """Select step t's hidden row BEFORE the vocab projection: a
    [rows,D]x[D,V] matmul instead of [rows,maxT,D]x[D,V] — identical
    logits, maxT-fold cheaper (shared by all decode builders)."""
    t_mask = layers.cast(layers.equal(positions, counter), "float32")
    step_hidden = layers.reduce_sum(
        layers.elementwise_mul(dec, layers.unsqueeze(t_mask, [1]),
                               axis=1), dim=1)
    return layers.fc(step_hidden, vocab, bias_attr=False,
                     param_attr="logits.w")


def init_token_buffer(src, positions, max_out_len, start_id):
    """[B, maxT] int64 zeros with the start token at position 0 — the
    loop-carried decode buffer the whole-loop builders share."""
    buf = layers.fill_constant_batch_size_like(
        src, [-1, max_out_len], "int64", 0.0)
    if start_id:
        start_col = layers.cast(
            layers.equal(positions,
                         layers.fill_constant([1], "int64", 0.0)),
            "int64")
        buf = layers.elementwise_add(
            buf, layers.cast(
                layers.scale(start_col, scale=float(start_id)),
                "int64"))
    return layers.assign(buf)


def emit_token_step(src, step_logits_v, positions, tgt_buf, finished,
                    counter, limit, cond, max_out_len, end_id):
    """Shared whole-loop decode tail: greedy argmax, EOS freeze
    (finished rows keep emitting end_id), one-hot write at position
    t+1, counter bump, loop-condition refresh. Mutates tgt_buf/
    finished/counter/cond in place — keep BOTH whole-loop builders on
    this helper so their token-for-token equivalence can't silently
    diverge.

    The refreshed condition carries an all-rows-finished early-exit
    term: once every row has emitted end_id the loop stops instead of
    spinning to max_out_len emitting frozen end_id rows. Positions
    past the exit step keep their zero init — callers that need the
    variable-length result go through apply_eos_sentinel
    (inference/serving.py), which normalizes everything after the
    first end_id to the -1 sentinel either way. Expressed with
    reduce_sum/elementwise_min/greater_than only, all inside the
    native xla_train kernel slice (FLAGS_native_build builds these
    programs too)."""
    tok = layers.cast(layers.argmax(step_logits_v, axis=-1), "int64")
    not_fin = layers.elementwise_sub(
        layers.fill_constant_batch_size_like(
            src, [-1], "int64", 1.0), finished)
    tok = layers.elementwise_add(
        layers.elementwise_mul(tok, not_fin),
        layers.cast(layers.scale(finished, scale=float(end_id)),
                    "int64"))
    layers.assign(
        layers.elementwise_max(
            finished,
            layers.cast(layers.equal(
                tok, layers.fill_constant([1], "int64",
                                          float(end_id))), "int64")),
        output=finished)
    next_mask = layers.cast(
        layers.equal(positions,
                     layers.increment(counter, 1, in_place=False)),
        "int64")
    keep = layers.elementwise_sub(
        layers.fill_constant([max_out_len], "int64", 1.0), next_mask)
    layers.assign(
        layers.elementwise_add(
            layers.elementwise_mul(tgt_buf, keep),
            layers.elementwise_mul(layers.unsqueeze(tok, [1]),
                                   next_mask)),
        output=tgt_buf)
    layers.increment(counter, 1)
    # continue while BOTH hold: steps remain (limit - counter > 0) AND
    # at least one row is unfinished (sum(1 - finished) > 0); min(a, b)
    # > 0 encodes the conjunction without logical ops
    unfinished = layers.reduce_sum(
        layers.elementwise_sub(
            layers.fill_constant_batch_size_like(
                src, [-1], "int64", 1.0), finished),
        keep_dim=True)
    layers.greater_than(
        layers.elementwise_min(
            layers.elementwise_sub(limit, counter), unfinished),
        layers.fill_constant([1], "int64", 0.0), cond=cond)


def heads_of(x, t, n_heads, head_dim):
    """[R,t,H*D] -> [R,H,t,D] (the cached-attention head layout every
    KV-cached decode builder shares)."""
    return layers.transpose(
        layers.reshape(x, [0, t, n_heads, head_dim]),
        perm=[0, 2, 1, 3])


# ---------------------------------------------------------------------------
# Cache-access objects: the ONE place layout differences live.
# ---------------------------------------------------------------------------
class _DenseLaneCache:
    """Per-layer dense self-KV access: in-place one-hot masked write
    into per-lane ``[R, H, maxT, Dh]`` vars, attention reads the vars
    directly (the r10 layout; write masks broadcast for either a
    shared scalar counter [maxT,1] or per-lane counters
    [R,1,maxT,1])."""

    def __init__(self, kc, vc, write_mask, keep_mask):
        self.kc, self.vc = kc, vc
        self.write_mask, self.keep_mask = write_mask, keep_mask

    def update(self, kh, vh):
        new_kc = layers.elementwise_add(
            layers.elementwise_mul(self.kc, self.keep_mask),
            layers.elementwise_mul(kh, self.write_mask))
        new_vc = layers.elementwise_add(
            layers.elementwise_mul(self.vc, self.keep_mask),
            layers.elementwise_mul(vh, self.write_mask))
        layers.assign(new_kc, output=self.kc)
        layers.assign(new_vc, output=self.vc)
        return self.kc, self.vc


class _PagedLaneCache:
    """Per-layer paged self-KV access: writes go through the
    ``masked_pool_write`` registry op (disjoint one-hot scatter into
    the SHARED ``[NB, BS, H, Dh]`` pool at each lane's block-table
    address, gated by the active mask so idle/dustbin lanes never
    touch the pool — the PTA110 exclusivity contract), reads gather
    every lane's maxT cache positions back into the dense
    ``[R, H, maxT, Dh]`` view the shared attention math expects.
    Positions a lane has not written yet hold stale pool bytes; the
    caller's validity bias (-1e9 past position t) masks them exactly
    like the dense layout masks its zeros, so the softmax sees
    identical values — token-exact parity with dense."""

    def __init__(self, pool_k, pool_v, write_idx, gate, flat_pos,
                 rows, n_heads, head_dim, maxT, n_cells):
        self.pool_k, self.pool_v = pool_k, pool_v
        self.write_idx, self.gate = write_idx, gate
        self.flat_pos = flat_pos          # [rows*maxT] int32 cell addrs
        self.rows, self.maxT = rows, maxT
        self.n_heads, self.head_dim = n_heads, head_dim
        self.n_cells = n_cells            # NB * BS

    def _view(self, pool):
        flat = layers.reshape(pool, [self.n_cells,
                                     self.n_heads * self.head_dim])
        rows_kv = layers.gather(flat, self.flat_pos)
        return layers.transpose(
            layers.reshape(rows_kv, [self.rows, self.maxT,
                                     self.n_heads, self.head_dim]),
            perm=[0, 2, 1, 3])

    def update(self, kh, vh):
        for pool, new in ((self.pool_k, kh), (self.pool_v, vh)):
            layers.masked_pool_write(
                pool,
                layers.reshape(new, [0, self.n_heads, self.head_dim]),
                self.write_idx, gate=self.gate, leading_dims=2,
                exclusive_via="block_table")
        return self._view(self.pool_k), self._view(self.pool_v)


class _DenseSpanCache:
    """Per-layer dense self-KV access for a MULTI-position write (the
    speculative verify step: q=k+1 query rows per lane land at cache
    positions t..t+k in one update). ``pos_oh`` is the [R,q,maxT]
    one-hot of each query's cache position (all-zero rows for
    positions past the buffer write nothing); the scatter is the
    one-hot matmul the admission bodies already use, and the read
    view is the raw var exactly like _DenseLaneCache."""

    def __init__(self, kc, vc, pos_oh, keep_mask):
        self.kc, self.vc = kc, vc
        # [R,1,maxT,q] scatter operand (matmul against [R,H,q,Dh])
        self.scat = layers.unsqueeze(
            layers.transpose(pos_oh, perm=[0, 2, 1]), [1])
        self.keep_mask = keep_mask  # [R,1,maxT,1]

    def update(self, kh, vh):
        for var, new in ((self.kc, kh), (self.vc, vh)):
            scat = layers.matmul(self.scat, new)  # [R,H,maxT,Dh]
            layers.assign(layers.elementwise_add(
                layers.elementwise_mul(var, self.keep_mask), scat),
                output=var)
        return self.kc, self.vc


class _PagedSpanCache:
    """Per-layer paged self-KV access for the multi-position verify
    write: the q positions of every lane flatten to R*q
    masked_pool_write rows (distinct cells — positions within a lane
    are distinct, lanes own disjoint blocks via the host table: the
    PTA110 exclusivity story is unchanged), with the gate extended by
    per-position validity so positions past the buffer end never
    touch the pool. Reads reuse the full dense-view gather."""

    def __init__(self, pool_k, pool_v, write_idx_rq, gate_rq,
                 flat_pos, rows, q, n_heads, head_dim, maxT, n_cells):
        self.pool_k, self.pool_v = pool_k, pool_v
        self.write_idx, self.gate = write_idx_rq, gate_rq  # [R*q]
        self.flat_pos = flat_pos
        self.rows, self.q, self.maxT = rows, q, maxT
        self.n_heads, self.head_dim = n_heads, head_dim
        self.n_cells = n_cells

    def _view(self, pool):
        flat = layers.reshape(pool, [self.n_cells,
                                     self.n_heads * self.head_dim])
        rows_kv = layers.gather(flat, self.flat_pos)
        return layers.transpose(
            layers.reshape(rows_kv, [self.rows, self.maxT,
                                     self.n_heads, self.head_dim]),
            perm=[0, 2, 1, 3])

    def update(self, kh, vh):
        for pool, new in ((self.pool_k, kh), (self.pool_v, vh)):
            # [R,H,q,Dh] -> [R*q, H, Dh] write rows
            rows_new = layers.reshape(
                layers.transpose(new, perm=[0, 2, 1, 3]),
                [self.rows * self.q, self.n_heads, self.head_dim])
            layers.masked_pool_write(
                pool, rows_new, self.write_idx, gate=self.gate,
                leading_dims=2, exclusive_via="block_table")
        return self._view(self.pool_k), self._view(self.pool_v)


@dataclass(frozen=True)
class SamplingConfig:
    """Emission-lane sampling policy (temperature/top-k/top-p) for a
    decode bundle. temperature == 0 degenerates to greedy argmax;
    ``base_seed`` is the bundle's noise root — per-request seeds fold
    into it, so two servers over the same weights with different
    base seeds sample independently. Noise derivation (and why the
    executor step key deliberately stays out of it):
    ops/spec_ops.py module docstring."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    base_seed: int = 0

    def validate(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if self.top_p <= 0 or self.top_p > 1.0:
            raise ValueError(f"top_p must be in (0, 1], got "
                             f"{self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def token(self) -> tuple:
        return ("sample", float(self.temperature), int(self.top_k),
                float(self.top_p), int(self.base_seed))


@dataclass(frozen=True)
class DraftConfig:
    """Draft model of a speculative (draft-and-verify) decode bundle
    (Leviathan et al.; the vLLM spec-decode worker family, PAPERS.md).
    The draft is a SMALLER enc-dec transformer co-resident with the
    target in ONE scope, so every parameter it creates is prefixed
    (``prefix``, default ``draft_``) — explicit names per the PTA050
    cross-build rule, and the builder pair-lints draft-vs-target
    persistable names with the PTA100 collision check at bundle
    build. ``k`` proposals per lane per step; k=0 degenerates to the
    plain one-token step (the r10 path).

    r19 adaptive-speculation knobs:

    * ``kind="ngram"`` replaces the draft MODEL with a model-free
      prompt-copy proposer: each tick proposes the continuation of
      the longest (up to ``ngram``-token) prompt/history suffix match
      ("prompt lookup decoding"; PAPERS.md). Proposals enter the SAME
      spec_accept verify path as deterministic one-hot
      "distributions" — exact under greedy AND sampled emission,
      because a one-hot draft distribution makes the Leviathan accept
      test exact (accept w.p. p(x); residual is p with x zeroed). No
      draft params, no draft KV, no draft model steps — the whole
      proposer is index arithmetic over per-lane prompt/history
      state.
    * ``k_options`` is the pre-built adaptive-k ladder: for every
      ``kv`` in it besides the default ``k``, the bundle builds a
      parallel serve-program set keyed ``("k", kv, base_key)`` over
      the SAME slot state, so the host controller
      (inference/spec_controller.py) re-buckets lanes across draft
      lengths by pure program selection — zero steady-state compiles
      by construction. ``k`` must itself be a rung of a non-empty
      ladder.
    * ``sharded`` opts the draft model INTO the bundle's tp plan
      (draft params + draft KV head-sharded). Default False: r17
      measured a sharded draft as all-overhead (a draft small enough
      to be cheap is small enough that its psums dominate), so the
      shipped placement shards only the TARGET.
    """

    d_model: int = 32
    n_heads: int = 2
    n_layers: int = 1
    d_inner: int = 64
    k: int = 3
    prefix: str = "draft_"
    kind: str = "model"       # "model" | "ngram"
    ngram: int = 2            # suffix-match length for kind="ngram"
    k_options: tuple = ()     # adaptive ladder; () = fixed-k bundle
    sharded: bool = False     # shard draft params/KV under tp

    def validate(self, max_out_len: int):
        if self.k < 0:
            raise ValueError(f"draft k must be >= 0, got {self.k}")
        if self.k + 1 > max_out_len:
            raise ValueError(
                f"draft k={self.k} proposes past the decode buffer "
                f"(max_out_len={max_out_len})")
        if self.kind not in ("model", "ngram"):
            raise ValueError(
                f"draft kind must be 'model' or 'ngram', got "
                f"{self.kind!r}")
        if self.kind == "ngram":
            if self.ngram < 1:
                raise ValueError(
                    f"ngram suffix length must be >= 1, got "
                    f"{self.ngram}")
            if self.sharded:
                raise ValueError(
                    "DraftConfig(kind='ngram') has no draft params "
                    "to shard — sharded=True is meaningless")
        elif self.d_model % self.n_heads:
            raise ValueError(
                f"draft d_model={self.d_model} not divisible by "
                f"n_heads={self.n_heads}")
        if self.k_options:
            opts = tuple(int(v) for v in self.k_options)
            if list(opts) != sorted(set(opts)):
                raise ValueError(
                    f"k_options must be sorted unique ints, got "
                    f"{self.k_options!r}")
            for kv in opts:
                if kv < 0 or kv + 1 > max_out_len:
                    raise ValueError(
                        f"k_options entry {kv} out of range for "
                        f"max_out_len={max_out_len}")
            if self.k not in opts:
                raise ValueError(
                    f"default k={self.k} must be a rung of "
                    f"k_options={self.k_options!r} (the serve keys "
                    f"the controller starts from)")
            if self.k == 0:
                raise ValueError(
                    "adaptive bundles need a speculative DEFAULT "
                    "(k > 0): the k=0 rung is the degradation "
                    "target, not the build anchor — every draft.k>0 "
                    "gate (state specs, admissions) keys off the "
                    "default")

    def token(self) -> tuple:
        return ("spec", int(self.k), int(self.d_model),
                int(self.n_heads), int(self.n_layers),
                int(self.d_inner), self.prefix, self.kind,
                int(self.ngram),
                tuple(int(v) for v in self.k_options),
                int(self.sharded))


def cached_decoder_step(x, caches, cross_kv, att_bias, d_model,
                        n_heads, d_inner, prefix="", q=1,
                        qkv_interleaved=False):
    """One KV-cached decoder-stack step over a [R,q,D] row batch
    (reference tests/unittests/dist_transformer.py:1498 fast_decode's
    cached decoder, factored so the whole-loop incremental program and
    the slot-pool single-step programs — dense AND paged — trace the
    IDENTICAL math; their token-for-token parity is structural, not
    coincidental).

    ``caches``: per-layer cache-access objects (_DenseLaneCache /
    _PagedLaneCache for q=1; the span caches for the speculative
    q=k+1 verify step) owning the self-attention KV write+view.
    ``cross_kv``: per-layer (ck, cv) [R,H,S,Dh] encoder projections
    (vars for dense, pool gathers for paged). ``att_bias`` is the
    0/-1e9 validity bias added to the [R,H,q,maxT] attention scores —
    for q>1 it must be per-query-position causal ([R,1,q,maxT]:
    query j masks cache positions > t+j). Param names are the
    explicit {prefix}dec{li}_* scheme shared with the training build
    (``prefix`` is how a speculative DRAFT model co-resides with the
    target in one scope without aliasing — the PTA100 contract).

    ``qkv_interleaved=True`` uses the head-interleaved fused weight
    ``{prefix}dec{li}_self_qkvh.w`` (columns ``[H, 3, Dh]``-major;
    see ShardingConfig and ``interleave_qkv_params``): the q/k/v
    decomposition becomes reshape → local split → squeeze →
    transpose, so the fused projection column-shards under tp with
    zero per-tick reshard. Identical math to the contiguous layout —
    only the weight column ORDER differs.
    Returns the [R,q,D] hidden rows after all layers.
    """
    from . import transformer as T

    head_dim = d_model // n_heads
    scale = head_dim ** -0.5
    for li, cache in enumerate(caches):
        # --- cached causal self-attention (fused qkv) ---
        if qkv_interleaved:
            qkv = layers.fc(
                x, 3 * d_model, num_flatten_dims=2, bias_attr=False,
                param_attr=T._attn_proj_attr(f"{prefix}dec{li}_self",
                                             "qkvh", d_model))
            # [R,q,3D] -> [R,q,H,3,Dh]: H rides the MAJOR position
            # of the split group, so a column shard on dim 2 of the
            # fc output carries to the H axis (sharding_rules
            # rule_reshape major-carry); the 3-way split is then on
            # the UNSHARDED interleave axis — entirely local
            z = layers.reshape(qkv, [0, q, n_heads, 3, head_dim])
            zq, zk, zv = layers.split(z, 3, dim=3)
            qh, kh, vh = (
                layers.transpose(layers.squeeze(t, axes=[3]),
                                 perm=[0, 2, 1, 3])
                for t in (zq, zk, zv))  # [R,H,q,Dh]
        else:
            qkv = layers.fc(
                x, 3 * d_model, num_flatten_dims=2, bias_attr=False,
                param_attr=T._attn_proj_attr(f"{prefix}dec{li}_self",
                                             "qkv", d_model))
            qv, k, v = layers.split(qkv, 3, dim=2)
            qh = heads_of(qv, q, n_heads, head_dim)
            kh = heads_of(k, q, n_heads, head_dim)
            vh = heads_of(v, q, n_heads, head_dim)
        kc, vc = cache.update(kh, vh)
        scores = layers.scale(
            layers.matmul(qh, kc, transpose_y=True),
            scale=scale)  # [R,H,q,maxT]
        scores = layers.elementwise_add(scores, att_bias)
        probs = layers.softmax(scores, axis=-1)
        ctx = layers.matmul(probs, vc)
        ctx = layers.reshape(
            layers.transpose(ctx, perm=[0, 2, 1, 3]),
            [0, q, d_model])  # [R,q,HD]
        attn_out = layers.fc(ctx, d_model, num_flatten_dims=2,
                             bias_attr=False,
                             param_attr=f"{prefix}dec{li}_self_out.w")
        x = T._add_norm(attn_out, x, 0.0, True,
                        name=f"{prefix}dec{li}_a")
        # --- cross attention against precomputed enc K/V ---
        q2 = layers.fc(
            x, d_model, num_flatten_dims=2, bias_attr=False,
            param_attr=T._attn_proj_attr(f"{prefix}dec{li}_cross",
                                         "q", d_model))
        q2h = heads_of(q2, q, n_heads, head_dim)
        ck, cv = cross_kv[li]
        s2 = layers.scale(
            layers.matmul(q2h, ck, transpose_y=True),
            scale=scale)  # [R,H,q,S]
        p2 = layers.softmax(s2, axis=-1)
        ctx2 = layers.reshape(
            layers.transpose(layers.matmul(p2, cv),
                             perm=[0, 2, 1, 3]),
            [0, q, d_model])
        cross_out = layers.fc(
            ctx2, d_model, num_flatten_dims=2,
            bias_attr=False,
            param_attr=f"{prefix}dec{li}_cross_out.w")
        x = T._add_norm(cross_out, x, 0.0, True,
                        name=f"{prefix}dec{li}_b")
        # --- ffn ---
        ffn = T._ffn(x, d_model, d_inner, 0.0, True,
                     name=f"{prefix}dec{li}")
        x = T._add_norm(ffn, x, 0.0, True, name=f"{prefix}dec{li}_c")
    return x


# ---------------------------------------------------------------------------
# Whole-loop fronts (scalar step counter; per-request programs).
# ---------------------------------------------------------------------------
def build_greedy_decode_program(seq_len=16, max_out_len=16,
                                d_model=64, n_heads=4, n_layers=2,
                                d_inner=128, vocab=1000, start_id=0,
                                end_id=1, sharding=None):
    """Autoregressive greedy generation (reference
    tests/unittests/dist_transformer.py:1498 fast_decode — its
    while-op beam loop, at beam 1 — rebuilt as a lax.while_loop over
    the full decoder at static shapes: each step re-runs the
    causally-masked decoder on the [B, max_out_len] token buffer and
    writes position t+1 by a one-hot mask; positions past t are
    ignored by the causal mask, so no KV cache is needed for
    correctness — incremental caching is a perf upgrade, not a
    semantics change). Rows that emit end_id are frozen: every later
    position holds end_id, like the reference's early-finish
    handling.

    Weight sharing with a training program is by EXPLICIT param name
    (enc{i}_*/dec{i}_*/logits.w/…_word_emb) — build order and
    unique_name state are irrelevant.
    Returns (program, startup, feeds, out_ids_var).
    """
    import paddle_tpu as fluid

    from . import transformer as T

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        enc = T._embed(src, vocab, d_model, max(seq_len, max_out_len),
                       0.0, True, "src_word_emb")
        for li in range(n_layers):
            enc = T.encoder_layer(enc, d_model, n_heads, d_inner, 0.0,
                                  is_test=True, name=f"enc{li}")

        positions = layers.cast(layers.range(0, max_out_len, 1),
                                "int64")
        tgt_buf = init_token_buffer(src, positions, max_out_len,
                                    start_id)
        # fixed-name counter so tests/benches can fetch the number of
        # loop iterations actually taken (the early-exit probe)
        counter = devtel.declare_decode_steps(main.global_block)
        limit = layers.fill_constant([1], "int64",
                                     float(max_out_len - 1))
        finished = layers.assign(layers.fill_constant_batch_size_like(
            src, [-1], "int64", 0.0))  # [B]: 1 once EOS emitted
        cond = layers.less_than(counter, limit)
        w = layers.While(cond)
        with w.block():
            dec = T._embed(tgt_buf, vocab, d_model,
                           max(seq_len, max_out_len), 0.0, True,
                           "tgt_word_emb")
            for li in range(n_layers):
                dec = T.decoder_layer(dec, enc, d_model, n_heads,
                                      d_inner, 0.0, is_test=True,
                                      name=f"dec{li}")
            logits_v = step_logits(dec, positions, counter,
                                   vocab)  # [B, V]
            emit_token_step(src, logits_v, positions, tgt_buf,
                            finished, counter, limit, cond,
                            max_out_len, end_id)
    if sharding is not None and sharding.enabled:
        sharding.validate(n_heads, vocab, d_model, d_inner)
        # params-only tp layout, mirroring the incremental front: the
        # full-recompute loop holds no persistable KV at all, so the
        # fused attention ops pick up head sharding purely from the
        # GSPMD-propagated param placements — which is exactly what
        # makes this front the sharded parity oracle for the paged
        # bundle (same placements, no cache layout to disagree on)
        annotate_sharded_program(
            main, tp_param_placements(n_layers, sharding),
            ((sharding.axis, sharding.tp),))
    return main, startup, ["src_ids"], tgt_buf


def build_incremental_decode_program(seq_len=16, max_out_len=16,
                                     d_model=64, n_heads=4,
                                     n_layers=2, d_inner=128,
                                     vocab=1000, start_id=0,
                                     end_id=1, sharding=None):
    """KV-cached autoregressive greedy generation — the incremental
    variant of build_greedy_decode_program (reference
    tests/unittests/dist_transformer.py:1498 fast_decode caches
    per-layer K/V the same way). Each step embeds ONE token, runs the
    decoder stack on that single row against cached self-attention
    K/V (written in place at position t) and precomputed
    cross-attention K/V, so per-step cost is O(maxT) instead of
    O(maxT^2) — token-for-token identical to the full-recompute
    program (asserted in tests).

    Weight sharing: the same explicit param names the training build
    and build_greedy_decode_program use — order-independent.

    Returns (program, startup, feeds, out_ids_var).
    """
    import paddle_tpu as fluid

    from . import transformer as T

    head_dim = d_model // n_heads
    maxT = max_out_len

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        enc = T._embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                       True, "src_word_emb")
        for li in range(n_layers):
            enc = T.encoder_layer(enc, d_model, n_heads, d_inner, 0.0,
                                  is_test=True, name=f"enc{li}")

        # cross-attention K/V once per layer (explicitly named
        # dec{li}_cross_kv.w, shared with the training build)
        cross_kv = []
        for li in range(n_layers):
            kv = layers.fc(enc, 2 * d_model, num_flatten_dims=2,
                           bias_attr=False,
                           param_attr=T._attn_proj_attr(
                               f"dec{li}_cross", "kv", d_model))
            k, v = layers.split(kv, 2, dim=2)
            cross_kv.append((heads_of(k, seq_len, n_heads, head_dim),
                             heads_of(v, seq_len, n_heads, head_dim)))

        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        posf = layers.cast(positions, "float32")
        pos_table = layers.assign(
            T._position_encoding(max(seq_len, maxT), d_model)[:maxT])

        tgt_buf = init_token_buffer(src, positions, maxT, start_id)
        # per-layer self-attn caches [B,H,maxT,D]
        caches = []
        for li in range(n_layers):
            kc = layers.assign(layers.fill_constant_batch_size_like(
                src, [-1, n_heads, maxT, head_dim], "float32", 0.0))
            vc = layers.assign(layers.fill_constant_batch_size_like(
                src, [-1, n_heads, maxT, head_dim], "float32", 0.0))
            caches.append((kc, vc))
        counter = devtel.declare_decode_steps(main.global_block)
        limit = layers.fill_constant([1], "int64", float(maxT - 1))
        finished = layers.assign(layers.fill_constant_batch_size_like(
            src, [-1], "int64", 0.0))
        cond = layers.less_than(counter, limit)
        w = layers.While(cond)
        with w.block():
            # embed ONLY the current token
            t_mask = layers.cast(layers.equal(positions, counter),
                                 "float32")  # [maxT]
            cur_tok = layers.reduce_sum(
                layers.elementwise_mul(tgt_buf,
                                       layers.cast(t_mask, "int64")),
                dim=1, keep_dim=True)  # [B,1]
            x = layers.embedding(cur_tok, size=[vocab, d_model],
                                 param_attr=ParamAttr(
                                     name="tgt_word_emb"))
            # lookup_table squeezes the trailing 1 of [B,1] ids:
            # restore the time axis for the [B,1,D] step row
            x = layers.unsqueeze(x, [1])
            x = layers.scale(x, scale=d_model ** 0.5)
            pos_t = layers.reduce_sum(
                layers.elementwise_mul(
                    pos_table, layers.unsqueeze(t_mask, [1]), axis=0),
                dim=0)  # [D]
            x = layers.elementwise_add(x, pos_t)  # [B,1,D]

            # attention validity: cached positions <= t
            att_mask = layers.scale(
                layers.cast(layers.greater_than(
                    posf, layers.cast(counter, "float32")),
                    "float32"), scale=-1e9)  # [maxT] 0 keep / -1e9 drop

            # one-hot write column at cache position t (axis 2 of the
            # [B,H,maxT,Dh] caches) and its complement
            m2 = layers.unsqueeze(t_mask, [1])  # [maxT,1]
            keepc = layers.unsqueeze(
                layers.elementwise_sub(
                    layers.fill_constant([maxT], "float32", 1.0),
                    t_mask), [1])
            cache_objs = [_DenseLaneCache(kc, vc, m2, keepc)
                          for kc, vc in caches]
            x = cached_decoder_step(x, cache_objs, cross_kv, att_mask,
                                    d_model, n_heads, d_inner)

            logits_v = layers.fc(
                layers.reshape(x, [0, d_model]), vocab,
                bias_attr=False, param_attr="logits.w")  # [B,V]
            emit_token_step(src, logits_v, positions, tgt_buf,
                            finished, counter, limit, cond, maxT,
                            end_id)
    if sharding is not None and sharding.enabled:
        sharding.validate(n_heads, vocab, d_model, d_inner)
        # params-only tp layout (the per-request KV caches here are
        # loop-local temporaries — the paged POOL is where per-device
        # KV bytes matter); the emit While's guard derives purely
        # from GSPMD-sharded values, which the prover classifies
        # value-uniform (absint GSPMD-uniform guards)
        annotate_sharded_program(
            main, tp_param_placements(n_layers, sharding),
            ((sharding.axis, sharding.tp),))
    return main, startup, ["src_ids"], tgt_buf


# ---------------------------------------------------------------------------
# Slot-pool front: bucketed admission + single-step/burst programs.
# ---------------------------------------------------------------------------
class DecodeStepBundle:
    """Program set for slot-pool continuous batching (reference
    tests/unittests/dist_transformer.py:1498 fast_decode is the decode
    loop; the slot-pool scheduling follows the iteration-level /
    paged-slot serving discipline of Orca (OSDI'22) and vLLM
    (SOSP'23), PAPERS.md).

    All per-slot decode state is PERSISTABLE scope state shared by the
    programs (KV cache, token buffers, per-slot step counters,
    finished/active lane masks — written by one-hot scatter, the
    repo's loop-carried-history convention). The pool holds
    ``n_slots`` schedulable lanes plus ONE extra dustbin row (index
    ``n_slots``) that absorbs the padded rows of a bucketed admission
    batch — it decodes garbage harmlessly (every op is row-wise, and
    under the paged layout its pool writes are gated off) and is
    never scheduled.

    KV layout is selected by ``cache`` (CacheConfig): ``dense``
    per-lane buffers, or ``paged`` shared block pools + per-lane
    block-table/prompt-entry indirection (module docstring). Under
    the paged layout the block table and prompt-entry references are
    HOST-owned read-only state: the serving scheduler allocates
    blocks/entries (HostBlockPool/PromptPrefixCache) and writes the
    tables into the scope between dispatches — the device programs
    never mutate them.

    * ``prefills[A]`` — one admission program per bucket size A
      (power-of-two ladder up to n_slots): feeds ``src_ids`` [A,
      seq_len] + ``slots`` [A] (dustbin index for padded rows); runs
      the encoder over the WHOLE admission batch, installs each row's
      cross-attention K/V (dense: one-hot matmul scatter into the
      lane rows; paged: masked_pool_write into the fed
      ``prompt_slots`` entries), resets the slots' decode state, and
      raises their active flags. ``prefill`` aliases the smallest
      bucket. Paged bundles also carry ``hit_prefills[A]`` —
      encoder-free admissions for prompts whose entry is already
      cached (the prefix-reuse fast path: lane reset only).
    * ``step`` — no feeds; advances EVERY lane one token in one
      dispatch via the shared ``cached_decoder_step`` body.
    * ``serves[key]`` — the fused scheduler-cycle programs: the
      admission body (absent at key 0) followed by a While that runs
      the step body until ``n_steps`` ticks ran or the live-lane
      count drops to ``min_active`` (both fed as [1] int64). Keys are
      admission buckets (ints) for dense bundles and ``("hit"|"miss",
      A)`` tuples (plus 0) for paged ones; ``serve_feed_spec(key)``
      names each program's feed signature. Chunked-prefill bundles
      (``cache.chunk_tokens > 0``) additionally carry ``("chunked",
      p)`` programs — phase p of the incremental encoder over ONE
      prompt chunk, fused with the same decode While so live lanes
      keep ticking while the chunk computes (the two-tier schedule).

    ``state`` maps logical names ('tok_buf', 'step', 'finished',
    'active', and for paged 'block_tab'/'prompt_ref') to the scope
    var names; ``init_slot_state(scope)`` seeds the pool. The
    returned ``startup`` holds param initializers only — serving runs
    against an already-trained scope and must NOT run it.

    Weight sharing: the explicit enc{i}_*/dec{i}_*/logits.w/…_word_emb
    names — order-independent with the train and whole-loop builds.
    """

    def __init__(self, prefills, step, serves, startup, state,
                 n_slots, seq_len, max_out_len, start_id, end_id,
                 cache=None, hit_prefills=None, sampling=None,
                 draft=None, cow=None, probe=None):
        self.prefills = dict(prefills)   # bucket size A -> Program
        self.prefill = self.prefills[min(self.prefills)]
        self.hit_prefills = dict(hit_prefills or {})
        self.step = step
        self.serves = dict(serves)       # key -> Program (see docstring)
        self.startup = startup
        self.state = dict(state)
        self.n_slots = n_slots
        self.dustbin = n_slots           # the padded-admission row
        self.seq_len = seq_len
        self.max_out_len = max_out_len
        self.start_id = start_id
        self.end_id = end_id
        self.cache = cache or CacheConfig()
        self.sampling = sampling         # SamplingConfig | None
        self.draft = draft               # DraftConfig | None
        self.cow = cow                   # COW block-copy Program
        self.probe = probe               # probe-step Program
        self.sharding = None             # ShardingConfig | None
        self.sharding_plan = None        # core.sharding_plan plan
        self._state_specs = {}

    def programs(self):
        """Every program of the bundle, in a stable order (prefills,
        hit prefills, step, serves) — the sweep surface for sharding
        annotation/placement and zoo registration."""
        out = [p for _a, p in sorted(self.prefills.items())]
        out += [p for _a, p in sorted(self.hit_prefills.items())]
        out.append(self.step)
        if self.cow is not None:
            out.append(self.cow)
        if self.probe is not None:
            out.append(self.probe)
        out += [p for _k, p in sorted(self.serves.items(),
                                      key=lambda kv: str(kv[0]))]
        return out

    @property
    def spec_k(self) -> int:
        """Draft proposals per lane per step (0 = plain decode)."""
        return self.draft.k if self.draft is not None else 0

    @property
    def spec_k_options(self) -> tuple:
        """The pre-built adaptive-k ladder (empty on fixed-k and
        plain bundles). Non-empty means serves carries a ("k", kv,
        base_key) variant set per non-default rung and the host
        controller may re-bucket across them compile-free."""
        if self.draft is None:
            return ()
        return tuple(int(v) for v in self.draft.k_options)

    @property
    def chunk_phase_keys(self):
        """The ("chunked", p) serve keys in phase order (empty on
        non-chunked bundles). The host drives ONE prompt through
        them phase-major: run phase p at EVERY chunk cursor before
        advancing to phase p+1 — attention phases read the full
        staged K/V of their layer, so a later phase may not start
        until the earlier one covered the whole prompt (the
        scheduler's chunk-job state machine walks exactly this
        order; total ticks = n_chunks * len(chunk_phase_keys))."""
        return sorted((k for k in self.serves
                       if isinstance(k, tuple) and k[0] == "chunked"),
                      key=lambda kv: kv[1])

    @property
    def tokens_per_tick(self) -> int:
        """Max tokens ONE device tick can emit per lane — the paged
        scheduler sizes block coverage by this (k accepted proposals
        + the correction/bonus token). Adaptive bundles size by the
        ladder's TOP rung: the controller may select it any
        dispatch."""
        return max((self.spec_k,) + self.spec_k_options) + 1

    @property
    def needs_seeds(self) -> bool:
        """True when admissions must feed per-request noise seeds
        (sampled emission lanes, or any speculative bundle — the
        acceptance draws are keyed on them)."""
        return self.sampling is not None or self.draft is not None

    def cache_token(self) -> tuple:
        """Content identity for server_fingerprint/compile-cache
        keys: KV layout (CacheConfig.token) PLUS the speculative and
        sampling configs — a spec bundle and a plain bundle over the
        same weights (or two spec bundles differing only in k or
        temperature) serve different token streams and must never
        dedupe or hot-swap as 'same model'."""
        tok = self.cache.token()
        if self.draft is not None:
            tok = tok + self.draft.token()
        if self.sampling is not None:
            tok = tok + self.sampling.token()
        if self.sharding is not None and self.sharding.enabled:
            # mesh shape + axis: a tp-sharded and a dense build over
            # the same weights serve different executables on
            # different device footprints — they must never dedupe
            # or hot-swap as "same model" (the plan token additionally
            # separates DEVICE slices at the compile-cache layer)
            tok = tok + self.sharding.token()
        return tok

    def serve_feed_spec(self, key) -> List[tuple]:
        """Feed signature (name, shape, dtype) of ``serves[key]`` —
        the serving layer binds prepared handles from this."""
        feed = [("n_steps", (1,), "int64"),
                ("min_active", (1,), "int64")]
        if isinstance(key, tuple) and key and key[0] == "k":
            # adaptive-k variant: same admission body, same slot
            # state, same feeds — only the burst's draft length
            # differs (the whole point: re-bucketing is pure program
            # selection)
            return self.serve_feed_spec(key[2])
        if key == 0:
            return feed
        tier, A = key if isinstance(key, tuple) else ("miss", key)
        if tier == "radix":
            pre = [("hist_toks", (A, self.max_out_len), "int64"),
                   ("resume_steps", (A,), "int64"),
                   ("prefill_until", (A,), "int64"),
                   ("slots", (A,), "int64")]
            if self.needs_seeds:
                pre.append(("seeds", (A,), "int64"))
            return pre + feed
        if tier == "chunked":
            # A is the PHASE index p here (0 = embed, 1+2l = layer
            # l's kv projection, 2+2l = layer l's attn+ffn, 2L+1 =
            # final cross-projection install)
            pre = [("chunk_entry", (1,), "int64"),
                   ("chunk_pos", (1,), "int64")]
            if A == 0:
                pre.append(("chunk_toks",
                            (1, self.cache.chunk_tokens), "int64"))
            return pre + feed
        pre = []
        if tier == "miss" or self.spec_k > 0:
            # spec bundles feed src_ids on HIT admissions too: the
            # (tiny) draft encoder always runs so its per-lane
            # cross-KV exists — only the TARGET encoder is skipped
            pre.append(("src_ids", (A, self.seq_len), "int64"))
        pre.append(("slots", (A,), "int64"))
        if tier == "miss" and self.cache.layout == "paged":
            pre.append(("prompt_slots", (A,), "int64"))
        if self.needs_seeds:
            pre.append(("seeds", (A,), "int64"))
        return pre + feed

    def cow_feed_spec(self) -> List[tuple]:
        """Feed signature of the COW block-copy program (``cow``):
        per-row (src shared block, dst fresh exclusive block, gate).
        Padded rows feed gate 0 and dst -1 (the trash row)."""
        rows = self.n_slots + 1
        return [("cow_src", (rows,), "int64"),
                ("cow_dst", (rows,), "int64"),
                ("cow_gate", (rows,), "float32")]

    def kv_state_bytes(self) -> int:
        """Total persistable KV bytes of the bundle (self + cross KV
        incl. table/indirection state; token/flag buffers excluded —
        identical across layouts). The capacity denominator for the
        requests-per-KV-byte bench metric."""
        total = 0
        for name, (shape, dt) in self._state_specs.items():
            short = name.split("/")[-1]
            if short.startswith(("self_", "cross_", "block_tab",
                                 "prompt_ref", "draft_self_",
                                 "draft_cross_")):
                total += int(np.prod(shape)) * np.dtype(dt).itemsize
        return total

    def init_slot_state(self, scope):
        """Seed the pool state in `scope` (idle slots: finished=1,
        active=0 — they step harmlessly until admitted; paged
        prompt_ref points every lane at the dustbin entry)."""
        for name, (shape, dt) in self._state_specs.items():
            if name == self.state["finished"]:
                scope._set(name, np.ones(shape, dt))
            elif name == self.state.get("prompt_ref"):
                scope._set(name, np.full(shape,
                                         self.cache.n_prompt_entries,
                                         dt))
            else:
                scope._set(name, np.zeros(shape, dt))


def _slot_state_specs(prefix, rows, maxT, seq_len, n_heads,
                      head_dim, n_layers, cache, sampling=None,
                      draft=None, vocab=None):
    specs = {
        f"{prefix}tok_buf": ((rows, maxT), "int64"),
        f"{prefix}step": ((rows,), "int64"),
        f"{prefix}finished": ((rows,), "int64"),
        f"{prefix}active": ((rows,), "int64"),
    }
    if sampling is not None or draft is not None:
        # per-lane noise seed, written at admission from the fed
        # per-request seeds — the (request, position) key channel
        specs[f"{prefix}seed"] = ((rows,), "int64")
    if draft is not None and draft.k > 0:
        if draft.kind == "model":
            dh = draft.d_model // draft.n_heads
            # the draft's self-KV stays DENSE per-lane in BOTH target
            # layouts (the draft is small — that is the point; paging
            # it would buy bytes nobody is short of), its cross-KV is
            # per-lane too (the draft encoder re-runs even on
            # prefix-HIT admissions, so no pooled entries to refcount)
            for li in range(draft.n_layers):
                specs[f"{prefix}draft_self_k{li}"] = (
                    (rows, draft.n_heads, maxT, dh), "float32")
                specs[f"{prefix}draft_self_v{li}"] = (
                    (rows, draft.n_heads, maxT, dh), "float32")
                specs[f"{prefix}draft_cross_k{li}"] = (
                    (rows, draft.n_heads, seq_len, dh), "float32")
                specs[f"{prefix}draft_cross_v{li}"] = (
                    (rows, draft.n_heads, seq_len, dh), "float32")
        else:
            # ngram proposer: no model, no KV — just the per-lane
            # prompt copy the suffix matcher scans (tok_buf already
            # holds the generated history)
            specs[f"{prefix}prompt_toks"] = ((rows, seq_len),
                                             "int64")
        # device-side speculative accounting ([1] int64 RMW counters;
        # the serving layer deltas them per dispatch): proposals
        # offered / accepted / tokens emitted / draft vs target model
        # steps — the observability satellite's raw series
        for c in ("spec_proposed", "spec_accepted", "spec_emitted",
                  "spec_draft_steps", "spec_target_steps"):
            specs[f"{prefix}{c}"] = ((1,), "int64")
        # PER-LANE acceptance accounting (the adaptive-k controller's
        # signal): accepted proposals and spec ticks per lane,
        # cumulative since init — the controller deltas them per
        # dispatch to estimate each lane's acceptance rate
        specs[f"{prefix}spec_lane_accepted"] = ((rows,), "int64")
        specs[f"{prefix}spec_lane_ticks"] = ((rows,), "int64")
        if draft.k_options:
            # per-rung tick counters for the pre-built k ladder
            # (@TEL: PTA180 contract, devtel fetch/stats for free)
            specs.update(devtel.spec_k_counter_specs(
                prefix, draft.k_options))
    # device-side flight data (observability/devtel.py): [1] int64
    # RMW counters every program of the bundle declares — ticks,
    # occupancy integral, burst exit reasons, admission-tier counts.
    # The @TEL name mark puts them under checker PTA180's contract.
    specs.update(devtel.counter_specs(prefix,
                                      cache.layout == "paged",
                                      chunked=cache.chunked))
    if cache.layout == "dense":
        for li in range(n_layers):
            specs[f"{prefix}self_k{li}"] = (
                (rows, n_heads, maxT, head_dim), "float32")
            specs[f"{prefix}self_v{li}"] = (
                (rows, n_heads, maxT, head_dim), "float32")
            specs[f"{prefix}cross_k{li}"] = (
                (rows, n_heads, seq_len, head_dim), "float32")
            specs[f"{prefix}cross_v{li}"] = (
                (rows, n_heads, seq_len, head_dim), "float32")
        return specs
    NP = cache.pages(maxT)
    E = cache.n_prompt_entries
    specs[f"{prefix}block_tab"] = ((rows, NP), "int32")
    specs[f"{prefix}prompt_ref"] = ((rows,), "int32")
    # teacher-forcing horizon per lane: while step+1 < prefill_until
    # the lane re-plays its (admission-written) token-buffer history —
    # KV is written, logits are computed, but the emitted token never
    # lands and EOS never latches. 0 (the idle/cold default) makes
    # every tick a real decode tick, so non-radix admissions are
    # untouched by construction. This is what lets a radix admission
    # chunk-prefill ONLY the divergent tail of a resumed chat turn.
    specs[f"{prefix}prefill_until"] = ((rows,), "int64")
    if cache.chunked:
        # chunked-prefill staging: per-PROMPT-ENTRY activation rows
        # the phase programs hand forward between ticks. The encoder
        # is bidirectional (layer l+1 needs ALL of layer l), so a
        # resumable prefill must stage whole-prompt activations —
        # indexed by prompt-entry id like the cross pools (+1
        # dustbin), NOT by lane: the entry is host-exclusive for the
        # whole prefill, and the staging row is dead once the final
        # phase installs the cross-KV. a/b ping-pong across layers;
        # kv holds the concat(K,V) self-attn projection of the layer
        # being chunked (attention needs K/V at ALL positions before
        # any query chunk can run — that is the phase split).
        d_model = n_heads * head_dim
        specs[f"{prefix}chunk_stage_a{POOL_MARK}"] = (
            (E + 1, seq_len, d_model), "float32")
        specs[f"{prefix}chunk_stage_b{POOL_MARK}"] = (
            (E + 1, seq_len, d_model), "float32")
        specs[f"{prefix}chunk_stage_kv{POOL_MARK}"] = (
            (E + 1, seq_len, 2 * d_model), "float32")
    if vocab is not None and (draft is None or draft.k == 0):
        # the beam/probe front's full next-token distribution, one
        # softmax row per lane, refreshed by the probe step program —
        # host-side beam branching reads it instead of re-running the
        # decoder outside the bundle
        specs[f"{prefix}probe_probs"] = ((rows, vocab), "float32")
    for li in range(n_layers):
        specs[f"{prefix}self_k{li}{POOL_MARK}"] = (
            (cache.n_blocks, cache.block_size, n_heads, head_dim),
            "float32")
        specs[f"{prefix}self_v{li}{POOL_MARK}"] = (
            (cache.n_blocks, cache.block_size, n_heads, head_dim),
            "float32")
        # +1: the dustbin entry padded admission rows scatter into
        specs[f"{prefix}cross_k{li}{POOL_MARK}"] = (
            (E + 1, n_heads, seq_len, head_dim), "float32")
        specs[f"{prefix}cross_v{li}{POOL_MARK}"] = (
            (E + 1, n_heads, seq_len, head_dim), "float32")
    return specs


def _declare_slot_state(block, specs):
    """Declare the persistable slot-pool vars in a program's global
    block (all programs bind the SAME scope values by name). Concrete
    shapes + dtypes keep them carry-declarable (checker PTA090)."""
    return {name: block.create_var(name=name, shape=shape, dtype=dt,
                                   persistable=True,
                                   stop_gradient=True)
            for name, (shape, dt) in specs.items()}


def tp_param_placements(n_layers: int, sharding: "ShardingConfig",
                        prefix: str = "") -> Dict[str, dict]:
    """{param name -> {dim: axis}} of the Megatron column/row-parallel
    decoder layout for the explicit ``{prefix}dec{li}_*`` name scheme
    (ShardingConfig docstring: the CONTIGUOUS fused qkv / fused
    cross-kv stay replicated — their fused-axis split crosses tp
    shard boundaries; biases stay replicated — GSPMD slices them
    locally for free). With ``sharding.qkv_interleaved`` the
    head-interleaved fused weight ``dec{li}_self_qkvh.w``
    column-shards: its ``[H, 3, Dh]``-major column order puts heads
    on the MAJOR axis of the decomposition reshape, so the shard
    carries through reshape/split/squeeze/transpose with zero
    reshard (the r17 leftover, closed)."""
    ax = sharding.axis
    out: Dict[str, dict] = {f"{prefix}logits.w": {1: ax}}
    for li in range(n_layers):
        if sharding.qkv_interleaved:
            out[f"{prefix}dec{li}_self_qkvh.w"] = {1: ax}
        out[f"{prefix}dec{li}_self_out.w"] = {0: ax}
        out[f"{prefix}dec{li}_cross_q.w"] = {1: ax}
        out[f"{prefix}dec{li}_cross_out.w"] = {0: ax}
        out[f"{prefix}dec{li}_fc1.w"] = {1: ax}
        out[f"{prefix}dec{li}_fc2.w"] = {0: ax}
    return out


def interleave_qkv_params(scope, n_layers: int, n_heads: int,
                          d_model: int, prefix: str = ""):
    """Convert trained CONTIGUOUS fused-qkv weights
    (``{prefix}dec{li}_self_qkv.w``, columns ``[3, H, Dh]``-major) to
    the HEAD-INTERLEAVED layout (``{prefix}dec{li}_self_qkvh.w``,
    columns ``[H, 3, Dh]``-major) a ``qkv_interleaved`` decode build
    reads — a pure column permutation, so the decode math is
    bit-identical to the contiguous layout (asserted by the bundle
    parity tests). Writes the converted weights into ``scope`` and
    returns the new param names. Reference counterpart:
    transpiler/distribute_transpiler.py:69 VarBlock param slicing —
    there a runtime program rewrite, here an offline weight re-layout
    feeding a declaratively sharded build."""
    head_dim = d_model // n_heads
    out = []
    for li in range(n_layers):
        src = f"{prefix}dec{li}_self_qkv.w"
        dst = f"{prefix}dec{li}_self_qkvh.w"
        w = np.asarray(scope._get(src))
        d_in = w.shape[0]
        scope._set(dst, np.ascontiguousarray(
            w.reshape(d_in, 3, n_heads, head_dim)
             .transpose(0, 2, 1, 3)
             .reshape(d_in, 3 * d_model)))
        out.append(dst)
    return out


def _tp_state_placements(state_prefix, n_layers, cache, sharding
                         ) -> Dict[str, dict]:
    """{slot-state name -> {dim: axis}}: KV sharded along heads (dim
    1 of the dense ``[R, H, T, Dh]`` lane buffers; dim 2 of the paged
    ``[NB, BS, H, Dh]`` self pool, dim 1 of the ``[E+1, H, S, Dh]``
    cross pool). Tables/masks/counters/draft state stay replicated —
    block tables in particular remain host-owned replicated int32, so
    the ownership story (PTA190/191) is untouched."""
    ax = sharding.axis
    out: Dict[str, dict] = {}
    for li in range(n_layers):
        if cache.layout == "dense":
            out[f"{state_prefix}self_k{li}"] = {1: ax}
            out[f"{state_prefix}self_v{li}"] = {1: ax}
            out[f"{state_prefix}cross_k{li}"] = {1: ax}
            out[f"{state_prefix}cross_v{li}"] = {1: ax}
        else:
            out[f"{state_prefix}self_k{li}{POOL_MARK}"] = {2: ax}
            out[f"{state_prefix}self_v{li}{POOL_MARK}"] = {2: ax}
            out[f"{state_prefix}cross_k{li}{POOL_MARK}"] = {1: ax}
            out[f"{state_prefix}cross_v{li}{POOL_MARK}"] = {1: ax}
    return out


def annotate_sharded_program(program, placements: Dict[str, dict],
                             mesh_axes, plan=None):
    """Wire ONE program into both halves of the sharded story from
    one placement table: the PROVER half (``absint.set_mesh`` + a
    ``mark_sharded`` pin per var present in the program, so
    PTA130/131/160/161 judge the real lowering) and the EXECUTION
    half (a shared ``core.sharding_plan.ShardingPlan`` attached for
    the Executor's jit in/out_shardings and cache-key tokens).
    Returns the plan (created when not passed) so a program family —
    every specialization of one bundle — shares one bind site."""
    from ..core import sharding_plan as sp

    absint.set_mesh(program,
                    absint.MeshConfig.make(**dict(mesh_axes)))
    blk = program.global_block
    for name, dims in placements.items():
        var = blk.vars.get(name) or blk._find_var_recursive(name)
        if var is None:
            continue  # this specialization never touches the var
        absint.mark_sharded(var, dims)
    if plan is None:
        plan = sp.ShardingPlan(tuple(mesh_axes), placements)
    sp.attach_plan(program, plan)
    return plan


def _apply_tp_sharding(bundle: "DecodeStepBundle",
                       sharding: "ShardingConfig", n_layers: int):
    """Annotate every program of a bundle with the tp layout and
    attach ONE shared execution plan (ShardingConfig docstring).
    The DRAFT model of a speculative bundle joins the plan only when
    ``draft.sharded`` opted it in (DraftConfig: r17 measured a
    sharded draft as all-overhead, so target-only is the default
    placement the controller hands out)."""
    placements = dict(tp_param_placements(n_layers, sharding))
    prefix = _state_prefix_of(bundle)
    placements.update(_tp_state_placements(
        prefix, n_layers, bundle.cache, sharding))
    draft = bundle.draft
    if draft is not None and draft.sharded and draft.k > 0:
        if draft.n_heads % sharding.tp or \
                draft.d_model % sharding.tp or \
                draft.d_inner % sharding.tp:
            raise ValueError(
                f"DraftConfig(sharded=True) needs draft "
                f"n_heads/d_model/d_inner divisible by tp="
                f"{sharding.tp}, got {draft.n_heads}/"
                f"{draft.d_model}/{draft.d_inner}")
        # the draft's fused qkv is never interleaved (it is not worth
        # a second weight layout for a model this small), so its
        # placements come from the contiguous view of the config
        dcfg = dataclasses.replace(sharding, qkv_interleaved=False)
        placements.update(tp_param_placements(
            draft.n_layers, dcfg, prefix=draft.prefix))
        # draft KV is dense per-lane [R, dH, T, dh] in both target
        # layouts — heads on dim 1
        for li in range(draft.n_layers):
            for nm in (f"draft_self_k{li}", f"draft_self_v{li}",
                       f"draft_cross_k{li}", f"draft_cross_v{li}"):
                placements[f"{prefix}{nm}"] = {1: sharding.axis}
    mesh_axes = ((sharding.axis, sharding.tp),)
    plan = None
    for prog in bundle.programs():
        plan = annotate_sharded_program(prog, placements, mesh_axes,
                                        plan=plan)
    bundle.sharding = sharding
    bundle.sharding_plan = plan
    return plan


def _state_prefix_of(bundle) -> str:
    """Recover the state prefix from any state entry ('@cb/' style:
    everything up to and including the last '/')."""
    name = bundle.state["tok_buf"]
    return name[:len(name) - len("tok_buf")]


def enc_param_placements(n_layers: int, sharding: "ShardingConfig",
                         prefix: str = "") -> Dict[str, dict]:
    """{param name -> {dim: axis}} for the ENCODER-side (prefill
    phase) stack: column/row-parallel ffn and row-parallel attention
    out-projections per encoder layer — prefill is MXU-bound, so the
    tp win is in the projection matmuls, where decode's plan
    (tp_param_placements) spends its placements on the KV bytes
    instead. The fused ``enc{l}_self_qkv.w`` and the cross-KV install
    ``dec{li}_cross_kv.w`` stay replicated for the same
    fused-axis-crosses-shards reason as the decoder's (ShardingConfig
    docstring)."""
    ax = sharding.axis
    out: Dict[str, dict] = {}
    for li in range(n_layers):
        out[f"{prefix}enc{li}_self_out.w"] = {0: ax}
        out[f"{prefix}enc{li}_fc1.w"] = {1: ax}
        out[f"{prefix}enc{li}_fc2.w"] = {0: ax}
    return out


def _prefill_state_placements(state_prefix, n_layers, cache, sharding
                              ) -> Dict[str, dict]:
    """Prefill-phase slot-state placements: the cross pools it WRITES
    sharded along heads (dim 1 of ``[E+1, H, S, Dh]`` — the same
    tensor layout the decode plan reads, so the handoff is a
    device_put, not a re-layout) plus the chunk staging pools along
    d_model (the heads-concat axis)."""
    ax = sharding.axis
    out: Dict[str, dict] = {}
    for li in range(n_layers):
        out[f"{state_prefix}cross_k{li}{POOL_MARK}"] = {1: ax}
        out[f"{state_prefix}cross_v{li}{POOL_MARK}"] = {1: ax}
    if cache.chunked:
        out[f"{state_prefix}chunk_stage_a{POOL_MARK}"] = {2: ax}
        out[f"{state_prefix}chunk_stage_b{POOL_MARK}"] = {2: ax}
        out[f"{state_prefix}chunk_stage_kv{POOL_MARK}"] = {2: ax}
    return out


def apply_phase_sharding(bundle: "DecodeStepBundle",
                         prefill_sharding: "ShardingConfig",
                         decode_sharding: "ShardingConfig",
                         n_layers: int):
    """Disaggregated prefill/decode sharding (DistServe, Zhong et al.
    OSDI'24 — PAPERS.md): the bundle's ``("chunked", p)`` phase
    programs get the PREFILL plan (MXU-bound: tp over the encoder
    projections, ``enc_param_placements``) while every other program
    gets the DECODE plan (bandwidth-bound: tp over KV bytes,
    ``tp_param_placements``) — two ``ShardingPlan``s whose tokens
    differ by placements AND, once bound to disjoint slices, by
    device ids, so no executable, disk-cache entry, or
    server_fingerprint can ever dedup across phases.

    Returns ``(prefill_plan, decode_plan)``. The decode plan is also
    attached as ``bundle.sharding_plan`` (what the serving layer's
    placement step binds); the prefill plan rides as
    ``bundle.prefill_plan`` and binds at
    ``runtime.placement.place_disaggregated_bundle``."""
    if not bundle.cache.chunked:
        raise ValueError(
            "apply_phase_sharding needs a chunked-prefill bundle "
            "(CacheConfig(chunk_tokens=C)) — without ('chunked', p) "
            "programs there is no prefill phase to carve out")
    prefix = _state_prefix_of(bundle)
    dec_placements = dict(tp_param_placements(n_layers,
                                              decode_sharding))
    dec_placements.update(_tp_state_placements(
        prefix, n_layers, bundle.cache, decode_sharding))
    pre_placements = dict(enc_param_placements(n_layers,
                                               prefill_sharding))
    pre_placements.update(_prefill_state_placements(
        prefix, n_layers, bundle.cache, prefill_sharding))
    dec_axes = ((decode_sharding.axis, decode_sharding.tp),)
    pre_axes = ((prefill_sharding.axis, prefill_sharding.tp),)
    dec_plan = None
    pre_plan = None
    chunk_progs = {id(p) for k, p in bundle.serves.items()
                   if isinstance(k, tuple) and k[0] == "chunked"}
    for prog in bundle.programs():
        if id(prog) in chunk_progs:
            pre_plan = annotate_sharded_program(
                prog, pre_placements, pre_axes, plan=pre_plan)
        else:
            dec_plan = annotate_sharded_program(
                prog, dec_placements, dec_axes, plan=dec_plan)
    pre_plan.label = "prefill"
    dec_plan.label = "decode"
    bundle.sharding = decode_sharding
    bundle.sharding_plan = dec_plan
    bundle.prefill_plan = pre_plan
    return pre_plan, dec_plan


def place_sharded_bundle(bundle: "DecodeStepBundle", scope,
                         devices=None) -> int:
    """The one-time serving placement step for a sharded bundle: bind
    the plan to a device slice (default: the first tp devices) and
    device_put EVERY persistable the bundle's programs read — sharded
    per the placement table, replicated otherwise — so steady-state
    dispatches never re-transfer params and per-device KV actually
    shrinks. Returns the number of arrays placed. Call AFTER params
    are trained/loaded and ``init_slot_state`` ran."""
    from ..core import sharding_plan as sp

    plan = getattr(bundle, "sharding_plan", None)
    if plan is None:
        raise ValueError("bundle has no sharding plan — build it "
                         "with ShardingConfig(tp>1)")
    ids_before = plan._device_ids
    plan.bind(devices)
    rebound = plan._device_ids != ids_before
    names = set(bundle._state_specs)
    for prog in bundle.programs():
        blk = prog.global_block
        for name, var in blk.vars.items():
            if var.persistable:
                names.add(name)
        # version-bump ONLY on a real (re)bind: prepared handles
        # bound against the old device slice must re-resolve, but a
        # second server over the SAME placement (fresh scope, same
        # slice) must hit the warmed executables — an unconditional
        # bump recompiled every serve program per server
        # construction (caught by bench.py sharded's zero-steady-
        # state-compiles assertion)
        if rebound or sp.plan_of(prog) is not plan:
            sp.attach_plan(prog, plan)
    return plan.place_state(scope, sorted(names))


def place_sharded_program(program, scope, devices=None) -> int:
    """``place_sharded_bundle`` for a single whole-loop program
    (build_incremental_decode_program(sharding=...)): bind the plan
    and device_put the program's persistables (params; the loop's KV
    caches are trace-local temporaries)."""
    from ..core import sharding_plan as sp

    plan = sp.plan_of(program)
    if plan is None:
        raise ValueError("program has no sharding plan — build it "
                         "with sharding=ShardingConfig(tp>1)")
    ids_before = plan._device_ids
    plan.bind(devices)
    names = sorted(v.name for v in program.list_vars()
                   if getattr(v, "persistable", False))
    if plan._device_ids != ids_before:
        sp.attach_plan(program, plan)  # re-bound: re-resolve handles
    return plan.place_state(scope, names)


def _param_probe(prefix, seq_len, max_out_len, d_model, n_heads,
                 n_layers, d_inner, vocab):
    """Tiny program whose only job is to CREATE every parameter the
    (prefix-named) enc-dec decode stack owns, through the REAL
    param-creating code paths (T.encoder_layer / cached_decoder_step /
    the embeddings and the logits fc) so the name set cannot drift
    from the actual builders — the draft-vs-target PTA100 pair lint
    (_pair_lint_draft_target) reads its persistables."""
    import paddle_tpu as fluid

    from . import transformer as T

    head_dim = d_model // n_heads
    maxT = max_out_len
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[1, seq_len],
                          dtype="int64", append_batch_size=False)
        enc = T._embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                       True, f"{prefix}src_word_emb")
        for li in range(n_layers):
            enc = T.encoder_layer(enc, d_model, n_heads, d_inner,
                                  0.0, is_test=True,
                                  name=f"{prefix}enc{li}")
        cross = []
        for li in range(n_layers):
            kvp = layers.fc(enc, 2 * d_model, num_flatten_dims=2,
                            bias_attr=False,
                            param_attr=T._attn_proj_attr(
                                f"{prefix}dec{li}_cross", "kv",
                                d_model))
            k, v = layers.split(kvp, 2, dim=2)
            cross.append((heads_of(k, seq_len, n_heads, head_dim),
                          heads_of(v, seq_len, n_heads, head_dim)))
        ids = layers.assign(np.zeros((1, 1), "int64"))
        x = layers.unsqueeze(
            layers.embedding(ids, size=[vocab, d_model],
                             param_attr=ParamAttr(
                                 name=f"{prefix}tgt_word_emb")), [1])
        wm = layers.assign(np.zeros((1, 1, maxT, 1), "float32"))
        km = layers.assign(np.ones((1, 1, maxT, 1), "float32"))
        caches = [
            _DenseLaneCache(
                layers.assign(np.zeros((1, n_heads, maxT, head_dim),
                                       "float32")),
                layers.assign(np.zeros((1, n_heads, maxT, head_dim),
                                       "float32")), wm, km)
            for _ in range(n_layers)]
        bias = layers.assign(np.zeros((maxT,), "float32"))
        x = cached_decoder_step(x, caches, cross, bias, d_model,
                                n_heads, d_inner, prefix=prefix)
        layers.fc(layers.reshape(x, [0, d_model]), vocab,
                  bias_attr=False, param_attr=f"{prefix}logits.w")
    return main


def _pair_lint_draft_target(draft, *, seq_len, max_out_len, d_model,
                            n_heads, n_layers, d_inner, vocab):
    """ModelRegistry-style PTA100 pair lint at bundle build: the
    speculative draft co-resides with the target in ONE scope, so ANY
    persistable name overlap between them is the aliasing/clobbering
    defect check_cross_model_collision exists for (same shape =
    silent weight aliasing — the draft would serve target weights and
    acceptance statistics would be garbage with no error anywhere).
    Raises with the formatted diagnostics on collision; a distinct
    ``draft.prefix`` keeps it silent."""
    from ..analysis.checkers import (ERROR,
                                     check_cross_model_collision,
                                     format_diagnostics)

    target = _param_probe("", seq_len, max_out_len, d_model, n_heads,
                          n_layers, d_inner, vocab)
    probe = _param_probe(draft.prefix, seq_len, max_out_len,
                         draft.d_model, draft.n_heads,
                         draft.n_layers, draft.d_inner, vocab)
    diags = [d for d in check_cross_model_collision(target, probe)
             if d.severity == ERROR]
    if diags:
        raise ValueError(
            f"speculative draft (prefix {draft.prefix!r}) collides "
            f"with the target model's persistables — co-residence in "
            f"one scope would alias/clobber weights (PTA100):\n"
            + format_diagnostics(diags))


def build_decode_step_program(seq_len=16, max_out_len=16, d_model=64,
                              n_heads=4, n_layers=2, d_inner=128,
                              vocab=1000, start_id=0, end_id=1,
                              n_slots=8, admit_buckets=None,
                              state_prefix="@cb/", cache=None,
                              sampling=None, draft=None,
                              sharding=None):
    """Build the slot-pool continuous-batching bundle (bucketed
    admission prefills + single-step decode over ``n_slots``
    device-resident lanes) — see DecodeStepBundle. The step program's
    per-layer math IS build_incremental_decode_program's While body
    (``cached_decoder_step``), with the scalar loop counter replaced
    by a per-lane counter vector, so a lane decodes token-for-token
    exactly what the whole-loop program would — the continuous
    server's parity invariant, across BOTH KV layouts.

    ``admit_buckets`` bounds the admission specializations (default:
    power-of-two ladder 1,2,4,... capped at n_slots); padded rows of
    a bucket land on the dustbin lane. ``cache`` (CacheConfig)
    selects the KV layout; None = dense.

    ``sampling`` (SamplingConfig) replaces the greedy argmax emission
    with temperature/top-k/top-p sampled lanes keyed on per-request
    seeds (admissions then feed ``seeds``); ``draft`` (DraftConfig)
    turns the step into SPECULATIVE draft-and-verify: k unrolled
    cached draft-model steps propose tokens per lane, ONE batched
    k+1-query target step verifies them, and per-lane counters
    advance by the accepted prefix (+ the correction/bonus token).
    Greedy spec (sampling None or temperature 0) is token-exact vs
    the whole-loop decode; sampled spec uses the rejection rule so
    the emitted stream matches the target model's (filtered)
    distribution. draft.k == 0 degenerates to the plain one-token
    step. The draft's params are prefix-named and pair-linted
    against the target's with the PTA100 collision check at build.

    Returns a DecodeStepBundle.
    """
    import paddle_tpu as fluid

    from . import transformer as T

    cache = cache or CacheConfig()
    cache.validate(max_out_len)
    if sharding is not None:
        sharding.validate(n_heads, vocab, d_model, d_inner)
    if sampling is not None:
        sampling.validate()
    if draft is not None:
        draft.validate(max_out_len)
        if draft.kind == "model":
            _pair_lint_draft_target(
                draft, seq_len=seq_len, max_out_len=max_out_len,
                d_model=d_model, n_heads=n_heads, n_layers=n_layers,
                d_inner=d_inner, vocab=vocab)
    spec = draft is not None and draft.k > 0
    ngram = spec and draft.kind == "ngram"
    qkv_il = sharding is not None and sharding.qkv_interleaved
    greedy = sampling is None or sampling.greedy
    samp = sampling or SamplingConfig(temperature=0.0)
    paged = cache.layout == "paged"
    needs_seeds = sampling is not None or draft is not None
    head_dim = d_model // n_heads
    maxT = max_out_len
    rows = n_slots + 1  # + the dustbin lane for padded admissions
    if admit_buckets is None:
        admit_buckets, b = [], 1
        while b < n_slots:
            admit_buckets.append(b)
            b *= 2
        admit_buckets.append(n_slots)
    admit_buckets = sorted(set(int(a) for a in admit_buckets))
    if admit_buckets[0] < 1 or admit_buckets[-1] > n_slots:
        raise ValueError(
            f"admit_buckets {admit_buckets} must lie in "
            f"[1, n_slots={n_slots}]")
    specs = _slot_state_specs(state_prefix, rows, maxT, seq_len,
                              n_heads, head_dim, n_layers, cache,
                              sampling=sampling, draft=draft,
                              vocab=vocab if paged else None)
    if paged:
        NP, BS, NB = cache.pages(maxT), cache.block_size, cache.n_blocks
        E = cache.n_prompt_entries

    # --- device-telemetry increment: var = var + delta on a bundle
    # counter (observability/devtel.py registry; silently skipped for
    # counters this layout does not carry, e.g. tel_admit_hit on
    # dense bundles) ------------------------------------------------
    def _tel_add(sv, logical, delta):
        var = sv.get(f"{state_prefix}{logical}{devtel.TEL_MARK}")
        if var is None:
            return
        layers.assign(layers.elementwise_add(var, delta), output=var)

    # --- ownership mint-site annotations (analysis/absint.py seed
    # table): every paged program declares the SAME host-owned index
    # sources, so the ownership prover (PTA190/191/192) can chain
    # each @POOL access back to the allocator invariant that makes it
    # lane-exclusive. block_tab rows are disjoint per lane
    # (HostBlockPool.alloc-disjoint, entries < NB), prompt_ref is the
    # REFCOUNTED read path (entries <= the dustbin at E), and the
    # active mask is the gate block-table writes must carry. ---------
    def _mark_ownership(sv):
        if not paged:
            return sv
        absint.mark_pool_index_source(
            sv[f"{state_prefix}block_tab"], "block_table", bound=NB)
        absint.mark_pool_index_source(
            sv[f"{state_prefix}prompt_ref"], "prompt_entry_ref",
            bound=E + 1)
        absint.mark_pool_index_source(
            sv[f"{state_prefix}active"], "lane_active")
        return sv

    # --- lane-reset tail shared by every admission flavor: one-hot
    # masks over the fed slot ids, then token-buffer/counter/flag
    # resets for exactly the admitted lanes --------------------------
    def _lane_onehots(slots, A):
        lane_range = layers.cast(layers.range(0, rows, 1), "int64")
        # [A, rows] one-hot per admitted prompt; padded rows all
        # point at the dustbin, whose scatter-sum is garbage by
        # design — min() clamps its multiplicity in the masks
        oh = layers.cast(
            layers.equal(lane_range,
                         layers.reshape(slots, [A, 1])),
            "float32")
        any_f = layers.elementwise_min(
            layers.reduce_sum(oh, dim=0),
            layers.fill_constant([rows], "float32", 1.0))
        any_i = layers.cast(any_f, "int64")
        keep_f = layers.elementwise_sub(
            layers.fill_constant([rows], "float32", 1.0), any_f)
        keep_i = layers.elementwise_sub(
            layers.fill_constant([rows], "int64", 1.0), any_i)
        return oh, any_f, any_i, keep_f, keep_i

    def _reset_lane_state(sv, any_i, keep_i, oh=None, seeds=None,
                          tier="miss"):
        # token buffer rows: start_id at position 0, zeros
        # elsewhere (identical init row for every admission)
        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        start_col = layers.cast(
            layers.equal(positions,
                         layers.fill_constant([1], "int64", 0.0)),
            "int64")
        row_init = layers.cast(
            layers.scale(start_col, scale=float(start_id)),
            "int64")
        any_col = layers.reshape(any_i, [rows, 1])
        keep_col = layers.reshape(keep_i, [rows, 1])
        tok_buf = sv[f"{state_prefix}tok_buf"]
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(tok_buf, keep_col),
            layers.elementwise_mul(any_col, row_init)),
            output=tok_buf)
        stepv = sv[f"{state_prefix}step"]
        layers.assign(layers.elementwise_mul(stepv, keep_i),
                      output=stepv)
        fin = sv[f"{state_prefix}finished"]
        layers.assign(layers.elementwise_mul(fin, keep_i),
                      output=fin)
        pfu = sv.get(f"{state_prefix}prefill_until")
        if pfu is not None:
            # admitted lanes start un-forced (a radix admission
            # re-scatters its horizon AFTER this shared reset)
            layers.assign(layers.elementwise_mul(pfu, keep_i),
                          output=pfu)
        if seeds is not None:
            # per-request noise seeds scatter to their lanes in PURE
            # int arithmetic (a float32 one-hot matmul would truncate
            # 32-bit seeds past 2^24); dustbin duplicates sum to
            # garbage harmlessly
            oh_i = layers.cast(oh, "int64")  # [A, rows]
            scat = layers.reduce_sum(
                layers.elementwise_mul(
                    oh_i, layers.reshape(seeds, [-1, 1])), dim=0)
            seedv = sv[f"{state_prefix}seed"]
            layers.assign(layers.elementwise_add(
                layers.elementwise_mul(seedv, keep_i), scat),
                output=seedv)
        act = sv[f"{state_prefix}active"]
        # the dustbin lane never activates: it must not hold the
        # serve While open nor count against min_active
        valid = layers.assign(
            (np.arange(rows) < n_slots).astype("int64"))
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(act, keep_i),
            layers.elementwise_mul(any_i, valid)), output=act)
        # devtel: count the REAL lanes this admission touched (padded
        # rows collapse onto the dustbin lane, masked out by `valid`)
        _tel_add(sv, f"tel_admit_{tier}",
                 layers.reduce_sum(
                     layers.elementwise_mul(any_i, valid),
                     keep_dim=True))

    def _seeds_data(A):
        if not needs_seeds:
            return None
        return layers.data("seeds", shape=[A], dtype="int64",
                           append_batch_size=False)

    def _draft_admit(sv, src, A, oh, keep_f):
        """Speculative admission tail: run the (tiny) DRAFT encoder
        over the admission prompts and install per-lane draft
        cross-KV + zeroed draft self-KV. Runs on EVERY admission
        flavor — including paged prefix-HITs, which skip only the
        TARGET encoder (the draft's cross-KV is per-lane, not
        pooled; re-encoding with the draft costs ~nothing, and
        pooling it would couple the prompt-entry refcounts to the
        draft's lifetime for no capacity win)."""
        dd = draft.d_model
        dh = dd // draft.n_heads
        denc = T._embed(src, vocab, dd, max(seq_len, maxT), 0.0,
                        True, f"{draft.prefix}src_word_emb")
        for li in range(draft.n_layers):
            denc = T.encoder_layer(denc, dd, draft.n_heads,
                                   draft.d_inner, 0.0, is_test=True,
                                   name=f"{draft.prefix}enc{li}")
        keep4 = layers.reshape(keep_f, [rows, 1, 1, 1])
        ohT = layers.transpose(oh, perm=[1, 0])  # [rows, A]
        flat = draft.n_heads * seq_len * dh
        for li in range(draft.n_layers):
            kvp = layers.fc(denc, 2 * dd, num_flatten_dims=2,
                            bias_attr=False,
                            param_attr=T._attn_proj_attr(
                                f"{draft.prefix}dec{li}_cross", "kv",
                                dd))
            k, v = layers.split(kvp, 2, dim=2)
            kh = heads_of(k, seq_len, draft.n_heads, dh)
            vh = heads_of(v, seq_len, draft.n_heads, dh)
            for var, new in (
                    (sv[f"{state_prefix}draft_cross_k{li}"], kh),
                    (sv[f"{state_prefix}draft_cross_v{li}"], vh)):
                scat = layers.reshape(
                    layers.matmul(ohT,
                                  layers.reshape(new, [A, flat])),
                    [rows, draft.n_heads, seq_len, dh])
                layers.assign(layers.elementwise_add(
                    layers.elementwise_mul(var, keep4), scat),
                    output=var)
            for var in (sv[f"{state_prefix}draft_self_k{li}"],
                        sv[f"{state_prefix}draft_self_v{li}"]):
                layers.assign(layers.elementwise_mul(var, keep4),
                              output=var)

    def _ngram_admit(sv, src, A, oh, keep_f):
        """Model-free draft admission: scatter the admission prompts
        into the admitted lanes' ``prompt_toks`` copies — the text
        the suffix matcher scans at every spec tick. Token ids <
        vocab << 2^24, so the float32 one-hot matmul scatter is exact
        (the radix hist_toks idiom)."""
        ohT = layers.transpose(oh, perm=[1, 0])            # [rows, A]
        scat = layers.cast(
            layers.matmul(ohT, layers.cast(src, "float32")),
            "int64")                                       # [R,S]
        keep_i = layers.cast(keep_f, "int64")
        keep_col = layers.reshape(keep_i, [rows, 1])
        var = sv[f"{state_prefix}prompt_toks"]
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(var, keep_col), scat),
            output=var)

    def _spec_admit(sv, src, A, oh, keep_f):
        """Speculative admission tail dispatch: draft-MODEL bundles
        install per-lane draft cross-KV (_draft_admit); ngram bundles
        install the per-lane prompt copy (_ngram_admit)."""
        if ngram:
            _ngram_admit(sv, src, A, oh, keep_f)
        else:
            _draft_admit(sv, src, A, oh, keep_f)

    def _encode_prompts(A):
        src = layers.data("src_ids", shape=[A, seq_len],
                          dtype="int64", append_batch_size=False)
        enc = T._embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                       True, "src_word_emb")
        for li in range(n_layers):
            enc = T.encoder_layer(enc, d_model, n_heads, d_inner,
                                  0.0, is_test=True,
                                  name=f"enc{li}")
        return src, enc

    def _cross_proj(enc, li):
        kvp = layers.fc(enc, 2 * d_model, num_flatten_dims=2,
                        bias_attr=False,
                        param_attr=T._attn_proj_attr(
                            f"dec{li}_cross", "kv", d_model))
        k, v = layers.split(kvp, 2, dim=2)
        return (heads_of(k, seq_len, n_heads, head_dim),
                heads_of(v, seq_len, n_heads, head_dim))

    # --- admission bodies: admit up to A prompts in ONE dispatch ----
    def _admit_body_dense(sv, A):
        src, enc = _encode_prompts(A)
        slots = layers.data("slots", shape=[A], dtype="int64",
                            append_batch_size=False)
        seeds = _seeds_data(A)
        oh, any_f, any_i, keep_f, keep_i = _lane_onehots(slots, A)
        keep4 = layers.reshape(keep_f, [rows, 1, 1, 1])
        ohT = layers.transpose(oh, perm=[1, 0])  # [rows, A]
        flat = n_heads * seq_len * head_dim
        for li in range(n_layers):
            kh, vh = _cross_proj(enc, li)
            for var, new in (
                    (sv[f"{state_prefix}cross_k{li}"], kh),
                    (sv[f"{state_prefix}cross_v{li}"], vh)):
                # one-hot matmul scatter: row a of `new` lands on
                # lane slots[a]; untouched lanes read 0 and keep
                # their old value through keep4
                scat = layers.reshape(
                    layers.matmul(ohT,
                                  layers.reshape(new, [A, flat])),
                    [rows, n_heads, seq_len, head_dim])
                layers.assign(layers.elementwise_add(
                    layers.elementwise_mul(var, keep4), scat),
                    output=var)
            for var in (sv[f"{state_prefix}self_k{li}"],
                        sv[f"{state_prefix}self_v{li}"]):
                layers.assign(layers.elementwise_mul(var, keep4),
                              output=var)
        if spec:
            _spec_admit(sv, src, A, oh, keep_f)
        _reset_lane_state(sv, any_i, keep_i, oh=oh, seeds=seeds)

    def _admit_body_paged_miss(sv, A):
        """Cold-prompt admission: encode, publish cross-KV into the
        fed prompt-pool entries (host-distinct indices — padded rows
        target the dustbin entry), reset the lanes. The lanes' block
        tables / prompt refs are HOST-written scope state."""
        src, enc = _encode_prompts(A)
        slots = layers.data("slots", shape=[A], dtype="int64",
                            append_batch_size=False)
        pslots = layers.data("prompt_slots", shape=[A], dtype="int64",
                             append_batch_size=False)
        # the scheduler feeds pairwise-distinct FRESH entries
        # (refcount==1 at write time; padded rows aim at the dustbin
        # E) — the host invariant PTA191 names in its proof
        absint.mark_pool_index_source(pslots, "host_indices",
                                      bound=E + 1)
        seeds = _seeds_data(A)
        for li in range(n_layers):
            kh, vh = _cross_proj(enc, li)
            for var, new in (
                    (sv[f"{state_prefix}cross_k{li}{POOL_MARK}"], kh),
                    (sv[f"{state_prefix}cross_v{li}{POOL_MARK}"],
                     vh)):
                layers.masked_pool_write(
                    var, new, pslots, leading_dims=1,
                    exclusive_via="host_indices")
        oh, _, any_i, keep_f, keep_i = _lane_onehots(slots, A)
        if spec:
            _spec_admit(sv, src, A, oh, keep_f)
        _reset_lane_state(sv, any_i, keep_i, oh=oh, seeds=seeds)
        # fresh lanes need no self-pool zeroing: every cache position
        # <= t is rewritten by the lane before it is ever attended to,
        # and positions > t are masked by the validity bias exactly
        # like the dense layout's zeros

    def _admit_body_paged_hit(sv, A):
        """Prefix-HIT admission: the prompt's cross-KV entry is
        already in the pool (refcount bumped host-side), so admission
        is a lane reset only — no TARGET encoder, no pool write. This
        is the prefix-reuse fast path a shared system prompt rides.
        Speculative bundles still feed src_ids here and run the
        (tiny) DRAFT encoder: its cross-KV is per-lane state (see
        _draft_admit)."""
        if spec:
            src = layers.data("src_ids", shape=[A, seq_len],
                              dtype="int64", append_batch_size=False)
        slots = layers.data("slots", shape=[A], dtype="int64",
                            append_batch_size=False)
        seeds = _seeds_data(A)
        oh, _, any_i, keep_f, keep_i = _lane_onehots(slots, A)
        if spec:
            _spec_admit(sv, src, A, oh, keep_f)
        _reset_lane_state(sv, any_i, keep_i, oh=oh, seeds=seeds,
                          tier="hit")

    def _admit_body_paged_radix(sv, A):
        """Radix-resume admission (multi-turn sessions / shared-chain
        fan-out): the prompt's cross-KV entry is pooled (prefix HIT —
        the session pin guarantees it) and the longest shared BLOCK
        prefix of the lane's token history is host-mapped read-only
        into its block table, so the device neither encodes nor
        replays those positions. Admission scatters the full token
        HISTORY into tok_buf, sets step = resume_steps (the first
        position NOT covered by shared blocks — every device write
        lands in a freshly allocated exclusive block, which is how
        PTA192's read-only-while-shared holds by construction) and
        prefill_until = the history length, so the divergent tail
        chunk-prefills via teacher forcing before real decoding
        starts."""
        hist = layers.data("hist_toks", shape=[A, maxT],
                           dtype="int64", append_batch_size=False)
        resume = layers.data("resume_steps", shape=[A], dtype="int64",
                             append_batch_size=False)
        until = layers.data("prefill_until", shape=[A], dtype="int64",
                            append_batch_size=False)
        slots = layers.data("slots", shape=[A], dtype="int64",
                            append_batch_size=False)
        seeds = _seeds_data(A)
        oh, _, any_i, keep_f, keep_i = _lane_onehots(slots, A)
        _reset_lane_state(sv, any_i, keep_i, oh=oh, seeds=seeds,
                          tier="radix")
        # overwrite the shared reset's cold-start row/counters with
        # the session history. Token ids < vocab << 2^24, so the
        # float32 one-hot matmul scatter is exact; the counters use
        # the pure-int scatter idiom (they share the seed path's
        # magnitude concern)
        ohT = layers.transpose(oh, perm=[1, 0])            # [rows, A]
        hist_scat = layers.cast(
            layers.matmul(ohT, layers.cast(hist, "float32")),
            "int64")                                       # [R,maxT]
        any_col = layers.reshape(any_i, [rows, 1])
        keep_col = layers.reshape(keep_i, [rows, 1])
        tok_buf = sv[f"{state_prefix}tok_buf"]
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(tok_buf, keep_col),
            layers.elementwise_mul(hist_scat, any_col)),
            output=tok_buf)
        oh_i = layers.cast(oh, "int64")
        for feed_v, state_name in ((resume, "step"),
                                   (until, "prefill_until")):
            var = sv[f"{state_prefix}{state_name}"]
            scat = layers.reduce_sum(
                layers.elementwise_mul(
                    oh_i, layers.reshape(feed_v, [-1, 1])), dim=0)
            layers.assign(layers.elementwise_add(
                layers.elementwise_mul(var, keep_i), scat),
                output=var)

    admit_bodies = {"miss": _admit_body_dense if not paged
                    else _admit_body_paged_miss}
    if paged:
        admit_bodies["hit"] = _admit_body_paged_hit
        if not spec:
            # the radix tier rides the plain paged step: speculative
            # decode advances counters by variable accepted lengths,
            # which the block-aligned resume arithmetic does not
            # model (and the draft's dense per-lane KV has no shared
            # prefix to reuse anyway)
            admit_bodies["radix"] = _admit_body_paged_radix

    prefills = {}
    hit_prefills = {}
    startup = None
    for A in admit_buckets:
        prog = fluid.Program()
        st = fluid.Program()
        with fluid.program_guard(prog, st):
            admit_bodies["miss"](
                _mark_ownership(
                    _declare_slot_state(prog.global_block, specs)), A)
        prefills[A] = prog
        startup = startup or st
        if paged:
            hprog = fluid.Program()
            with fluid.program_guard(hprog, fluid.Program()):
                admit_bodies["hit"](
                    _mark_ownership(_declare_slot_state(
                        hprog.global_block, specs)), A)
            hit_prefills[A] = hprog

    # --- the one-token step body over all lanes (shared by the
    # standalone step program and the fused serve programs' While) ---
    def _step_body(sv, probe=False):
        tok_buf = sv[f"{state_prefix}tok_buf"]
        stepv = sv[f"{state_prefix}step"]
        fin = sv[f"{state_prefix}finished"]
        act = sv[f"{state_prefix}active"]
        # devtel: one tick ran; occupancy integral reads act BEFORE
        # this tick's retirements mutate it (live lanes AT tick start)
        _tel_add(sv, "tel_ticks",
                 layers.fill_constant([1], "int64", 1.0))
        _tel_add(sv, "tel_occupancy",
                 layers.reduce_sum(act, keep_dim=True))
        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        posf = layers.cast(positions, "float32")
        pos_table = layers.assign(
            T._position_encoding(max(seq_len, maxT), d_model)[:maxT])
        step2 = layers.reshape(stepv, [rows, 1])           # [R,1]
        t_mask = layers.cast(layers.equal(positions, step2),
                             "float32")                    # [R,maxT]
        cur_tok = layers.reduce_sum(
            layers.elementwise_mul(tok_buf,
                                   layers.cast(t_mask, "int64")),
            dim=1, keep_dim=True)                          # [R,1]
        x = layers.embedding(cur_tok, size=[vocab, d_model],
                             param_attr=ParamAttr(
                                 name="tgt_word_emb"))     # [R,D]
        x = layers.unsqueeze(x, [1])                       # [R,1,D]
        x = layers.scale(x, scale=d_model ** 0.5)
        pos_t = layers.matmul(t_mask, pos_table)           # [R,D]
        x = layers.elementwise_add(x, layers.unsqueeze(pos_t, [1]))
        # per-lane attention validity (paged gathers exactly the
        # dense maxT positions — block_size divides maxT — so the
        # same bias masks unwritten cells in both layouts)
        att_bias = layers.reshape(
            layers.scale(layers.cast(layers.greater_than(
                posf, layers.cast(step2, "float32")), "float32"),
                scale=-1e9),
            [rows, 1, 1, maxT])
        if not paged:
            write_mask = layers.reshape(t_mask, [rows, 1, maxT, 1])
            keep_mask = layers.reshape(
                layers.elementwise_sub(
                    layers.fill_constant([rows, maxT], "float32",
                                         1.0),
                    t_mask),
                [rows, 1, maxT, 1])
            caches = [_DenseLaneCache(sv[f"{state_prefix}self_k{li}"],
                                      sv[f"{state_prefix}self_v{li}"],
                                      write_mask, keep_mask)
                      for li in range(n_layers)]
            cross_kv = [(sv[f"{state_prefix}cross_k{li}"],
                         sv[f"{state_prefix}cross_v{li}"])
                        for li in range(n_layers)]
        else:
            # cell addresses through the HOST-owned block table:
            # flat cache cell of position p = tab[lane, p//BS]*BS
            # + p%BS, materialized for all maxT positions (gather
            # view) and for the current write position (scatter)
            tabf = layers.cast(sv[f"{state_prefix}block_tab"],
                               "float32")                  # [R,NP]
            base = layers.expand(
                layers.unsqueeze(layers.scale(tabf, scale=float(BS)),
                                 [2]),
                [1, 1, BS])                                # [R,NP,BS]
            offs = layers.assign(np.arange(BS, dtype="float32"))
            flat_posf = layers.elementwise_add(base, offs, axis=2)
            flat_pos = layers.cast(
                layers.reshape(flat_posf, [rows * maxT]), "int32")
            # current position's page/offset one-hots from t_mask
            t_pages = layers.reshape(t_mask, [rows, NP, BS])
            page_oh = layers.reduce_sum(t_pages, dim=2)    # [R,NP]
            off_oh = layers.reduce_sum(t_pages, dim=1)     # [R,BS]
            cur_block = layers.reduce_sum(
                layers.elementwise_mul(tabf, page_oh), dim=1)
            cur_off = layers.reduce_sum(
                layers.elementwise_mul(off_oh, offs), dim=1)
            write_idx = layers.cast(
                layers.elementwise_add(
                    layers.scale(cur_block, scale=float(BS)),
                    cur_off), "int32")                     # [R]
            # idle/dustbin/paused lanes (act=0) must NOT write the
            # SHARED pool — the gate is the lane-exclusivity half
            # PTA110 checks alongside the block-table indices
            gate = layers.cast(act, "float32")
            caches = [_PagedLaneCache(
                sv[f"{state_prefix}self_k{li}{POOL_MARK}"],
                sv[f"{state_prefix}self_v{li}{POOL_MARK}"],
                write_idx, gate, flat_pos, rows, n_heads, head_dim,
                maxT, NB * BS) for li in range(n_layers)]
            pref = sv[f"{state_prefix}prompt_ref"]
            cross_kv = []
            for li in range(n_layers):
                pair = []
                for tag in ("k", "v"):
                    pool = sv[f"{state_prefix}cross_{tag}{li}"
                              f"{POOL_MARK}"]
                    flat = layers.reshape(
                        pool, [E + 1, n_heads * seq_len * head_dim])
                    got = layers.gather(flat, pref)        # [R, HSD]
                    pair.append(layers.reshape(
                        got, [rows, n_heads, seq_len, head_dim]))
                cross_kv.append(tuple(pair))
        x = cached_decoder_step(x, caches, cross_kv, att_bias,
                                d_model, n_heads, d_inner,
                                qkv_interleaved=qkv_il)
        logits_v = layers.fc(
            layers.reshape(x, [0, d_model]), vocab,
            bias_attr=False, param_attr="logits.w")        # [R,V]
        if probe:
            # beam/probe front: publish every lane's full next-token
            # distribution for the HOST to branch on (the paged beam
            # decoder's expansion oracle — host selection, device KV)
            layers.assign(layers.softmax(logits_v),
                          output=sv[f"{state_prefix}probe_probs"])
        # --- per-lane emit (the emit_token_step tail, vectorized over
        # lane counters; same freeze/write semantics). Sampled lanes
        # draw from the filtered distribution keyed on (per-request
        # seed, position) — invariant to admission order / burst
        # boundaries / which serve specialization runs the tick
        # (ops/spec_ops.py noise discipline) ---
        ones_n = layers.fill_constant([rows], "int64", 1.0)
        if sampling is not None and not sampling.greedy:
            probs_v = layers.filtered_softmax(
                logits_v, temperature=samp.temperature,
                top_k=samp.top_k, top_p=samp.top_p)
            tok = layers.sample_categorical(
                probs_v, sv[f"{state_prefix}seed"],
                layers.elementwise_add(stepv, ones_n),
                noise_tag=0, base_seed=samp.base_seed)     # [R]
        else:
            tok = layers.cast(layers.argmax(logits_v, axis=-1),
                              "int64")                     # [R]
        not_fin = layers.elementwise_sub(ones_n, fin)
        tok = layers.elementwise_add(
            layers.elementwise_mul(tok, not_fin),
            layers.cast(layers.scale(fin, scale=float(end_id)),
                        "int64"))
        # teacher forcing (radix tail prefill / beam probe): while
        # step+1 < prefill_until the lane is REPLAYING its history —
        # the decoder ran and its KV write landed (that is the whole
        # point), but the emitted token must not clobber the history
        # token already sitting at step+1, and a coincidental end_id
        # must not latch fin. prefill_until defaults to 0 everywhere,
        # so non-radix lanes take emit_flag == act identically to the
        # pre-forcing lowering.
        emit_flag = ones_n
        if paged:
            forcing = layers.elementwise_mul(
                act, layers.cast(layers.less_than(
                    layers.elementwise_add(stepv, ones_n),
                    sv[f"{state_prefix}prefill_until"]), "int64"))
            emit_flag = layers.elementwise_sub(ones_n, forcing)
        # the EOS latch only counts lanes that actually ADVANCED this
        # tick (act gate): a host-paused paged lane (no KV block for
        # its next write) decodes a garbage token — its tok_buf write
        # is re-done correctly on resume, but an un-gated fin latch
        # would freeze the lane on garbage-EOS permanently
        new_fin = layers.elementwise_max(
            fin, layers.elementwise_mul(
                layers.elementwise_mul(act, emit_flag),
                layers.cast(layers.equal(
                    tok, layers.fill_constant(
                        [1], "int64", float(end_id))), "int64")))
        next2 = layers.reshape(
            layers.elementwise_add(stepv, ones_n), [rows, 1])
        next_mask = layers.cast(layers.equal(positions, next2),
                                "int64")                   # [R,maxT]
        next_mask = layers.elementwise_mul(
            next_mask, layers.reshape(emit_flag, [rows, 1]))
        keep_tok = layers.elementwise_sub(
            layers.fill_constant([rows, maxT], "int64", 1.0),
            next_mask)
        new_step = layers.elementwise_add(stepv, act)  # gate by lane
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(tok_buf, keep_tok),
            layers.elementwise_mul(next_mask,
                                   layers.reshape(tok, [rows, 1]))),
            output=tok_buf)
        layers.assign(new_step, output=stepv)
        # lanes auto-deactivate on EOS or buffer exhaustion — the
        # host retires a lane the moment its active flag drops
        room = layers.cast(layers.less_than(
            new_step, layers.fill_constant([1], "int64",
                                           float(maxT - 1))),
            "int64")                                       # [N]
        new_act = layers.elementwise_mul(
            layers.elementwise_mul(
                act, layers.elementwise_sub(ones_n, new_fin)),
            room)
        layers.assign(new_act, output=act)
        layers.assign(new_fin, output=fin)

    # --- the speculative (draft-and-verify) step body: k unrolled
    # cached DRAFT steps propose tokens per lane, ONE batched
    # (k+1)-query TARGET step verifies them, and spec_accept advances
    # each lane by its accepted prefix + the correction/bonus token.
    # Greedy is token-exact vs the whole-loop decode (the acceptance
    # rule degenerates exactly — ops/spec_ops.py); KV cells past the
    # accepted prefix hold rejected-token garbage, which is masked by
    # the per-query validity bias and rewritten when the lane reaches
    # those positions (the same staleness discipline the paged
    # layout already relies on). ------------------------------------
    def _spec_step_body(sv, k_run=None):
        # k_run: the draft length THIS serve variant runs (adaptive-k
        # ladder rungs share the body builder; None = the default k)
        k = draft.k if k_run is None else int(k_run)
        Q = k + 1
        tok_buf = sv[f"{state_prefix}tok_buf"]
        stepv = sv[f"{state_prefix}step"]
        fin = sv[f"{state_prefix}finished"]
        act = sv[f"{state_prefix}active"]
        seedv = sv[f"{state_prefix}seed"]
        # devtel: same tick/occupancy discipline as _step_body (act
        # read before the post-verify state assigns)
        _tel_add(sv, "tel_ticks",
                 layers.fill_constant([1], "int64", 1.0))
        _tel_add(sv, "tel_occupancy",
                 layers.reduce_sum(act, keep_dim=True))
        # adaptive ladder: which rung ticked (absent on fixed-k
        # bundles — _tel_add skips missing counters)
        _tel_add(sv, devtel.spec_k_logical(k),
                 layers.fill_constant([1], "int64", 1.0))
        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        posf = layers.cast(positions, "float32")
        pos_table = layers.assign(
            T._position_encoding(max(seq_len, maxT), d_model)[:maxT])
        ones_n = layers.fill_constant([rows], "int64", 1.0)
        step2 = layers.reshape(stepv, [rows, 1])           # [R,1]
        t_mask0 = layers.cast(layers.equal(positions, step2),
                              "float32")                   # [R,maxT]
        cur_tok = layers.reduce_sum(
            layers.elementwise_mul(tok_buf,
                                   layers.cast(t_mask0, "int64")),
            dim=1, keep_dim=True)                          # [R,1]

        if ngram:
            # ---- model-free propose (prompt-lookup decoding): find
            # the RIGHTMOST non-trivial occurrence of the lane's
            # last-n-token suffix in prompt+history and propose its
            # continuation. The proposals are deterministic, and
            # their one-hot "distributions" make the Leviathan
            # accept test exact under greedy AND sampled emission
            # (accept w.p. p(x); residual = p with x zeroed), so the
            # whole proposer is FREE of model steps — index
            # arithmetic only.
            n = draft.ngram
            S_ = seq_len
            CTX = S_ + maxT
            ctx_i = layers.concat(
                [sv[f"{state_prefix}prompt_toks"], tok_buf],
                axis=1)                                    # [R,CTX]
            ctx_f = layers.cast(ctx_i, "float32")
            ctx_posf = layers.assign(
                np.arange(CTX, dtype="float32"))           # [CTX]
            step2f = layers.cast(step2, "float32")         # [R,1]
            # candidate match-END validity: j >= n-1 (a full suffix
            # sits to its left) AND j < S + step (strictly left of
            # the live suffix end — excludes the trivial self-match
            # and, because validity is prefix-closed, every
            # uncommitted tok_buf position the window could touch)
            j_ok = layers.cast(layers.greater_than(
                ctx_posf, layers.fill_constant(
                    [1], "float32", float(n - 2))),
                "float32")                                 # [CTX]
            end_ok = layers.cast(layers.less_than(
                ctx_posf, layers.scale(step2f, bias=float(S_))),
                "float32")                                 # [R,CTX]
            score = layers.elementwise_mul(end_ok, j_ok, axis=1)
            for i in range(n):
                # suffix token i back from the live end: ctx[S+step-i]
                # — reading the CONCATENATED prompt+history means the
                # suffix crosses the prompt boundary correctly during
                # the first n generated tokens. A spurious match
                # against pad/zero tokens merely proposes tokens the
                # verify step then rejects (acceptance cost, never a
                # correctness cost).
                m_i = layers.cast(layers.equal(
                    ctx_posf, layers.scale(
                        step2f, bias=float(S_ - i))), "float32")
                s_i = layers.reduce_sum(
                    layers.elementwise_mul(ctx_f, m_i), dim=1,
                    keep_dim=True)                         # [R,1]
                # ctx shifted right by i (matmul with the off-
                # diagonal identity): shifted[r, j] = ctx[r, j-i]
                shift = layers.assign(
                    np.eye(CTX, dtype="float32", k=i))
                shifted = layers.matmul(ctx_f, shift)      # [R,CTX]
                score = layers.elementwise_mul(
                    score, layers.cast(layers.equal(shifted, s_i),
                                       "float32"))
            # rightmost match end: argmax of score*(j+1); 0 = none
            best = layers.reduce_max(
                layers.elementwise_mul(
                    score, layers.scale(ctx_posf, bias=1.0),
                    axis=1),
                dim=1, keep_dim=True)                      # [R,1]
            has = layers.cast(layers.greater_than(
                best, layers.fill_constant([1], "float32", 0.0)),
                "float32")                                 # [R,1]
            idx = layers.scale(best, bias=-1.0)            # [R,1]
            cur_f = layers.cast(cur_tok, "float32")        # [R,1]
            proposals, dprob_rows = [], []
            for m in range(k):
                pm = layers.scale(idx, bias=float(1 + m))  # [R,1]
                # committed-continuation gate: the proposed position
                # must itself be prompt/history (pm <= S+step)
                ok_m = layers.elementwise_mul(
                    has, layers.cast(layers.less_than(
                        pm, layers.scale(step2f,
                                         bias=float(S_ + 1))),
                        "float32"))                        # [R,1]
                om = layers.cast(layers.equal(ctx_posf, pm),
                                 "float32")                # [R,CTX]
                got = layers.reduce_sum(
                    layers.elementwise_mul(ctx_f, om), dim=1,
                    keep_dim=True)                         # [R,1]
                # fallback: repeat the current token (any proposal
                # is CORRECT — the verify step rejects bad ones; the
                # fallback only matters for acceptance rate)
                tok_m = layers.cast(layers.reshape(
                    layers.elementwise_add(
                        layers.elementwise_mul(got, ok_m),
                        layers.elementwise_mul(
                            cur_f, layers.scale(ok_m, scale=-1.0,
                                                bias=1.0))),
                    [rows]), "int64")                      # [R]
                proposals.append(tok_m)
                dprob_rows.append(layers.unsqueeze(
                    layers.one_hot(tok_m, vocab), [1]))    # [R,1,V]
        else:
            dd, dH = draft.d_model, draft.n_heads
            dpos_table = layers.assign(
                T._position_encoding(max(seq_len, maxT), dd)[:maxT])
            # ---- draft propose: k+1 unrolled cached draft-model
            # steps over positions step..step+k. Steps 0..k-1 yield
            # the k proposals; step k exists ONLY to write the
            # draft's KV at position step+k — after a full-acceptance
            # tick the counter advances to step+k+1, and without that
            # write the draft cache keeps a PERMANENT hole at step+k
            # (never reprocessed: later ticks start past it),
            # silently poisoning every subsequent proposal for the
            # lane's lifetime (measured: acceptance collapsed to ~0
            # after the first burst). The same discipline is why the
            # adaptive k=0 rung keeps a one-step draft keepalive
            # (_draft_keepalive) in front of the plain body. ----
            proposals, dprob_rows = [], []
            prev = cur_tok
            for j in range(k + 1):
                stepj = stepv if j == 0 else layers.elementwise_add(
                    stepv, layers.fill_constant([1], "int64",
                                                float(j)))
                stepj2 = layers.reshape(stepj, [rows, 1])
                t_mask_j = layers.cast(
                    layers.equal(positions, stepj2),
                    "float32")                             # [R,maxT]
                x = layers.embedding(prev, size=[vocab, dd],
                                     param_attr=ParamAttr(
                                         name=f"{draft.prefix}"
                                              f"tgt_word_emb"))
                x = layers.unsqueeze(x, [1])               # [R,1,dd]
                x = layers.scale(x, scale=dd ** 0.5)
                pos_e = layers.matmul(t_mask_j, dpos_table)
                x = layers.elementwise_add(
                    x, layers.unsqueeze(pos_e, [1]))
                dbias = layers.reshape(
                    layers.scale(layers.cast(layers.greater_than(
                        posf, layers.cast(stepj2, "float32")),
                        "float32"), scale=-1e9),
                    [rows, 1, 1, maxT])
                wm = layers.reshape(t_mask_j, [rows, 1, maxT, 1])
                km = layers.reshape(
                    layers.elementwise_sub(
                        layers.fill_constant([rows, maxT], "float32",
                                             1.0), t_mask_j),
                    [rows, 1, maxT, 1])
                dcaches = [
                    _DenseLaneCache(
                        sv[f"{state_prefix}draft_self_k{li}"],
                        sv[f"{state_prefix}draft_self_v{li}"],
                        wm, km)
                    for li in range(draft.n_layers)]
                dcross = [(sv[f"{state_prefix}draft_cross_k{li}"],
                           sv[f"{state_prefix}draft_cross_v{li}"])
                          for li in range(draft.n_layers)]
                x = cached_decoder_step(x, dcaches, dcross, dbias,
                                        dd, dH, draft.d_inner,
                                        prefix=draft.prefix)
                if j == k:
                    # the cache-fill-only step: position step+k's KV
                    # is written (the full-acceptance hole), no
                    # proposal
                    break
                dlogits = layers.fc(
                    layers.reshape(x, [0, dd]), vocab,
                    bias_attr=False,
                    param_attr=f"{draft.prefix}logits.w")  # [R,V]
                dprobs = layers.filtered_softmax(
                    dlogits, temperature=samp.temperature,
                    top_k=samp.top_k, top_p=samp.top_p)
                if greedy:
                    tok_j = layers.cast(
                        layers.argmax(dprobs, axis=-1), "int64")
                else:
                    tok_j = layers.sample_categorical(
                        dprobs, seedv,
                        layers.elementwise_add(
                            stepj, layers.fill_constant(
                                [1], "int64", 1.0)),
                        noise_tag=1, base_seed=samp.base_seed)
                proposals.append(tok_j)
                dprob_rows.append(layers.unsqueeze(dprobs, [1]))
                prev = layers.reshape(tok_j, [rows, 1])

        # ---- target verify: ONE batched Q-query cached step over
        # [current token, k proposals] ----
        toks_q = layers.concat(
            [cur_tok] + [layers.reshape(t, [rows, 1])
                         for t in proposals], axis=1)      # [R,Q]
        x = layers.embedding(toks_q, size=[vocab, d_model],
                             param_attr=ParamAttr(
                                 name="tgt_word_emb"))     # [R,Q,D]
        x = layers.scale(x, scale=d_model ** 0.5)
        posq = layers.elementwise_add(
            step2, layers.assign(np.arange(Q).astype("int64")))
        posq3 = layers.reshape(posq, [rows, Q, 1])
        t_mask_q = layers.cast(layers.equal(positions, posq3),
                               "float32")                  # [R,Q,maxT]
        x = layers.elementwise_add(
            x, layers.matmul(t_mask_q, pos_table))         # [R,Q,D]
        # per-query causal validity: query j attends positions
        # <= step+j (positions past the buffer get all-zero one-hots
        # and never write — see the span caches)
        bias = layers.reshape(
            layers.scale(layers.cast(layers.greater_than(
                posf, layers.cast(posq3, "float32")), "float32"),
                scale=-1e9),
            [rows, 1, Q, maxT])
        if not paged:
            keep = layers.reshape(
                layers.elementwise_sub(
                    layers.fill_constant([rows, maxT], "float32",
                                         1.0),
                    layers.reduce_sum(t_mask_q, dim=1)),
                [rows, 1, maxT, 1])
            caches = [_DenseSpanCache(
                sv[f"{state_prefix}self_k{li}"],
                sv[f"{state_prefix}self_v{li}"], t_mask_q, keep)
                for li in range(n_layers)]
            cross_kv = [(sv[f"{state_prefix}cross_k{li}"],
                         sv[f"{state_prefix}cross_v{li}"])
                        for li in range(n_layers)]
        else:
            tabf = layers.cast(sv[f"{state_prefix}block_tab"],
                               "float32")                  # [R,NP]
            base = layers.expand(
                layers.unsqueeze(layers.scale(tabf, scale=float(BS)),
                                 [2]),
                [1, 1, BS])                                # [R,NP,BS]
            offs = layers.assign(np.arange(BS, dtype="float32"))
            flat_pos = layers.cast(
                layers.reshape(
                    layers.elementwise_add(base, offs, axis=2),
                    [rows * maxT]), "int32")
            t_pages_q = layers.reshape(t_mask_q, [rows, Q, NP, BS])
            page_oh = layers.reduce_sum(t_pages_q, dim=3)  # [R,Q,NP]
            off_oh = layers.reduce_sum(t_pages_q, dim=2)   # [R,Q,BS]
            cur_block = layers.reduce_sum(
                layers.elementwise_mul(layers.unsqueeze(tabf, [1]),
                                       page_oh), dim=2)    # [R,Q]
            cur_off = layers.reduce_sum(
                layers.elementwise_mul(off_oh, offs), dim=2)
            write_idx = layers.cast(
                layers.reshape(
                    layers.elementwise_add(
                        layers.scale(cur_block, scale=float(BS)),
                        cur_off), [rows * Q]), "int32")
            # gate = active AND position-in-buffer: an out-of-range
            # query's one-hot is all-zero, which would otherwise
            # alias cell 0 of block 0 — another lane's KV
            validq = layers.reduce_sum(t_mask_q, dim=2)    # [R,Q]
            gate = layers.reshape(
                layers.elementwise_mul(
                    layers.reshape(layers.cast(act, "float32"),
                                   [rows, 1]), validq), [rows * Q])
            caches = [_PagedSpanCache(
                sv[f"{state_prefix}self_k{li}{POOL_MARK}"],
                sv[f"{state_prefix}self_v{li}{POOL_MARK}"],
                write_idx, gate, flat_pos, rows, Q, n_heads,
                head_dim, maxT, NB * BS) for li in range(n_layers)]
            pref = sv[f"{state_prefix}prompt_ref"]
            cross_kv = []
            for li in range(n_layers):
                pair = []
                for tag in ("k", "v"):
                    pool = sv[f"{state_prefix}cross_{tag}{li}"
                              f"{POOL_MARK}"]
                    flat = layers.reshape(
                        pool, [E + 1, n_heads * seq_len * head_dim])
                    got = layers.gather(flat, pref)
                    pair.append(layers.reshape(
                        got, [rows, n_heads, seq_len, head_dim]))
                cross_kv.append(tuple(pair))
        x = cached_decoder_step(x, caches, cross_kv, bias, d_model,
                                n_heads, d_inner, q=Q,
                                qkv_interleaved=qkv_il)    # [R,Q,D]
        logits_q = layers.fc(x, vocab, num_flatten_dims=2,
                             bias_attr=False,
                             param_attr="logits.w")        # [R,Q,V]
        tprobs = layers.filtered_softmax(
            logits_q, temperature=samp.temperature,
            top_k=samp.top_k, top_p=samp.top_p)
        dprobs_s = layers.concat(dprob_rows, axis=1)       # [R,k,V]
        props = layers.concat(
            [layers.reshape(t, [rows, 1]) for t in proposals],
            axis=1)                                        # [R,k]
        adv, toks, accepted, fin_new = layers.spec_accept(
            props, dprobs_s, tprobs, seedv, stepv, k=k,
            end_id=end_id, max_len=maxT, greedy=greedy,
            base_seed=samp.base_seed, noise_tag=8)
        adv_g = layers.elementwise_mul(adv, act)           # [R]
        layers.span_scatter(tok_buf, toks,
                            layers.elementwise_add(stepv, ones_n),
                            adv_g)
        new_fin = layers.elementwise_max(
            fin, layers.elementwise_mul(fin_new, act))
        new_step = layers.elementwise_add(stepv, adv_g)
        room = layers.cast(layers.less_than(
            new_step, layers.fill_constant([1], "int64",
                                           float(maxT - 1))),
            "int64")
        new_act = layers.elementwise_mul(
            layers.elementwise_mul(
                act, layers.elementwise_sub(ones_n, new_fin)), room)
        # ---- device-side speculative accounting (the serving layer
        # deltas these per dispatch). Computed BEFORE the state
        # assigns: the in-place act update below would otherwise feed
        # the POST-tick mask into this tick's live/accepted sums ----
        live = layers.reduce_sum(act, keep_dim=True)       # [1]
        k_const = layers.fill_constant([1], "int64", float(k))
        one_c = layers.fill_constant([1], "int64", 1.0)
        acc_live = layers.elementwise_mul(accepted, act)   # [R]
        bumps = [
            ("spec_proposed",
             layers.elementwise_mul(live, k_const)),
            ("spec_accepted",
             layers.reduce_sum(acc_live, keep_dim=True)),
            ("spec_emitted",
             layers.reduce_sum(adv_g, keep_dim=True)),
            ("spec_target_steps", one_c)]
        if not ngram:
            # the n-gram lane runs ZERO draft-model steps — keeping
            # this counter honest is what makes the devtel
            # draft/target step ratio meaningful per flavor
            bumps.append(("spec_draft_steps", k_const))
        # per-lane acceptance telemetry: the host controller
        # (inference/spec_controller.py) deltas these each dispatch
        # to re-bucket lanes across the pre-built k ladder
        bumps.append(("spec_lane_accepted", acc_live))
        bumps.append(("spec_lane_ticks", act))
        for name, delta in bumps:
            var = sv[f"{state_prefix}{name}"]
            layers.assign(layers.elementwise_add(var, delta),
                          output=var)
        layers.assign(new_step, output=stepv)
        layers.assign(new_act, output=act)
        layers.assign(new_fin, output=fin)

    def _draft_keepalive(sv):
        # adaptive k=0 rung, model drafts only: run ONE cached draft
        # step at the current position (output dead-coded by XLA)
        # purely to keep the draft KV cache hole-free. Without it a
        # lane parked at k=0 advances its counter past positions the
        # draft never processed, and every later re-promotion to
        # k>0 proposes from a holey cache — the same permanent-hole
        # failure mode as skipping the j==k cache-fill step.
        dd, dH = draft.d_model, draft.n_heads
        stepv = sv[f"{state_prefix}step"]
        tok_buf = sv[f"{state_prefix}tok_buf"]
        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        posf = layers.cast(positions, "float32")
        dpos_table = layers.assign(
            T._position_encoding(max(seq_len, maxT), dd)[:maxT])
        step2 = layers.reshape(stepv, [rows, 1])
        t_mask = layers.cast(layers.equal(positions, step2),
                             "float32")                    # [R,maxT]
        cur_tok = layers.reduce_sum(
            layers.elementwise_mul(tok_buf,
                                   layers.cast(t_mask, "int64")),
            dim=1, keep_dim=True)                          # [R,1]
        x = layers.embedding(cur_tok, size=[vocab, dd],
                             param_attr=ParamAttr(
                                 name=f"{draft.prefix}tgt_word_emb"))
        x = layers.unsqueeze(x, [1])
        x = layers.scale(x, scale=dd ** 0.5)
        pos_e = layers.matmul(t_mask, dpos_table)
        x = layers.elementwise_add(x, layers.unsqueeze(pos_e, [1]))
        dbias = layers.reshape(
            layers.scale(layers.cast(layers.greater_than(
                posf, layers.cast(step2, "float32")), "float32"),
                scale=-1e9),
            [rows, 1, 1, maxT])
        wm = layers.reshape(t_mask, [rows, 1, maxT, 1])
        km = layers.reshape(
            layers.elementwise_sub(
                layers.fill_constant([rows, maxT], "float32", 1.0),
                t_mask),
            [rows, 1, maxT, 1])
        dcaches = [
            _DenseLaneCache(sv[f"{state_prefix}draft_self_k{li}"],
                            sv[f"{state_prefix}draft_self_v{li}"],
                            wm, km)
            for li in range(draft.n_layers)]
        dcross = [(sv[f"{state_prefix}draft_cross_k{li}"],
                   sv[f"{state_prefix}draft_cross_v{li}"])
                  for li in range(draft.n_layers)]
        cached_decoder_step(x, dcaches, dcross, dbias, dd, dH,
                            draft.d_inner, prefix=draft.prefix)

    def _k0_body(sv):
        # graceful k->0 degradation: the plain (non-speculative) step
        # body — one target step, one token — plus the draft-cache
        # keepalive for model drafts. Spec scalar/lane counters are
        # deliberately NOT bumped (nothing proposed, nothing
        # verified); only the per-k tick counter records residency.
        if draft.kind == "model":
            _draft_keepalive(sv)
        _step_body(sv)
        _tel_add(sv, devtel.spec_k_logical(0),
                 layers.fill_constant([1], "int64", 1.0))

    body = _spec_step_body if spec else _step_body

    # --- standalone single-step program (one tick = one dispatch;
    # also the Executor.prepare(steps=K) scan target) ----------------
    step_prog = fluid.Program()
    with fluid.program_guard(step_prog, fluid.Program()):
        body(_mark_ownership(
            _declare_slot_state(step_prog.global_block, specs)))

    # --- fused serve programs: [admission +] a decode-burst While —
    # a WHOLE scheduler cycle (admit + burst) is ONE dispatch, so the
    # host overhead amortizes over A admissions and a burst of tokens
    # per lane. The loop exits when EITHER n_steps ticks ran OR the
    # live-lane count drops to min_active (both fed): with a
    # non-empty host queue the server sets min_active = live - 1, so
    # control returns the MOMENT a lane retires and its slot refills
    # — iteration-level scheduling with zero zombie ticks — while an
    # empty queue sets min_active = 0 and the burst drains the pool.
    # One specialization per admission flavor x bucket (0: no
    # admission). ---------------------------------------------------
    def _build_serve(tier, A, step_body=None):
        def pre(sv):
            if A > 0:
                admit_bodies[tier](sv, A)
        return _serve_program(pre, step_body)

    def _serve_program(pre_body, step_body=None):
        # step_body overrides the bundle's default tick body — the
        # adaptive-k serve variants swap in _spec_step_body(k=kv) or
        # _k0_body while sharing the SAME slot-state specs, so
        # controller re-bucketing is pure program selection (all
        # executables built up front, zero steady-state compiles)
        step_body = body if step_body is None else step_body
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            sv = _mark_ownership(
                _declare_slot_state(prog.global_block, specs))
            pre_body(sv)
            n_steps = layers.data("n_steps", shape=[1], dtype="int64",
                                  append_batch_size=False)
            min_active = layers.data("min_active", shape=[1],
                                     dtype="int64",
                                     append_batch_size=False)
            act = sv[f"{state_prefix}active"]
            k = layers.fill_constant([1], "int64", 0)

            def _serve_cond(cond=None):
                # ticks remain AND live lanes exceed the exit
                # threshold: min(a, b) > 0
                out = layers.greater_than(
                    layers.elementwise_min(
                        layers.elementwise_sub(n_steps, k),
                        layers.elementwise_sub(
                            layers.reduce_sum(act, keep_dim=True),
                            min_active)),
                    layers.fill_constant([1], "int64", 0.0),
                    cond=cond)
                # divergence-source annotation (analysis/absint.py
                # seed table): this predicate derives from the
                # per-lane active mask — the moment a lowering shards
                # LANES across a mesh axis it differs per device,
                # and the burst While becomes divergent control
                # flow. The prover (PTA130/131) uses the mark to
                # REJECT collectives/sharded values inside the burst
                # with a proof instead of a pattern guess. axes=
                # names the lane-sharding axis: on a tp-only mesh
                # (heads sharded, lanes replicated) the mark is
                # provably inert and the guard classifies from its
                # actual inputs — which is what lets the tp-sharded
                # serve programs carry their vocab-psum INSIDE the
                # burst legally (GSPMD-uniform control flow), while
                # any future lanes-sharding mesh flips this back to
                # proven-divergent automatically.
                absint.mark_divergence_source(out, "lane_active_mask",
                                              axes=(LANE_AXIS,))
                return out

            cond = _serve_cond()
            w = layers.While(cond)
            with w.block():
                step_body(sv)
                layers.increment(k, 1)
                _serve_cond(cond=cond)
            # devtel: classify THIS burst's exit exactly once, after
            # the While (k and act read their final loop values).
            # Precedence: ran all n_steps ticks > every lane idle >
            # live dropped to min_active — int arithmetic only, no
            # logical ops (the emit_token_step conjunction idiom)
            ran_out = layers.cast(layers.equal(k, n_steps), "int64")
            live = layers.reduce_sum(act, keep_dim=True)
            idle = layers.cast(
                layers.equal(live,
                             layers.fill_constant([1], "int64", 0.0)),
                "int64")
            one = layers.fill_constant([1], "int64", 1.0)
            not_ran = layers.elementwise_sub(one, ran_out)
            _tel_add(sv, "tel_exit_n_steps", ran_out)
            _tel_add(sv, "tel_exit_all_idle",
                     layers.elementwise_mul(not_ran, idle))
            _tel_add(sv, "tel_exit_min_active",
                     layers.elementwise_mul(
                         not_ran,
                         layers.elementwise_sub(one, idle)))
        return prog

    # --- chunked-prefill phase bodies (cache.chunk_tokens > 0): the
    # miss admission's encoder, re-cut into resumable C-token ticks.
    # The encoder is BIDIRECTIONAL — layer l+1 needs layer l at ALL
    # prompt positions — so "C tokens per tick" must be phase-major:
    # phase p runs over every chunk cursor before phase p+1 starts.
    # Phases: 0 = embed+positional into stage_a; 1+2l = layer l's
    # fused qkv projection of a chunk into stage_kv (per-position —
    # chunkable); 2+2l = layer l's attention (C queries over the FULL
    # staged K/V) + add_norm + ffn + add_norm into the other stage;
    # 2L+1 = the per-layer cross-KV projection of a chunk, installed
    # into the prompt entry's cross pools. Every op is per-position
    # outside `layers.attention`, and the attention phase reads
    # complete staged K/V, so the chunked pipeline is BIT-EXACT vs
    # the monolithic _admit_body_paged_miss encoder (asserted in
    # tests) — which is what lets a chunk-prefilled entry finish as
    # an ordinary prefix HIT. Ragged last chunks need no extra
    # masking: an out-of-range cursor row of the chunk-selection
    # one-hot is all-zero, so its scatter contributes nothing and
    # `keep` preserves the row.
    def _chunk_phase_body(sv, p):
        C, S, L = cache.chunk_tokens, seq_len, n_layers
        entry = layers.data("chunk_entry", shape=[1], dtype="int64",
                            append_batch_size=False)
        pos0 = layers.data("chunk_pos", shape=[1], dtype="int64",
                           append_batch_size=False)
        # mint-site ownership marks: the entry is a host-FRESH
        # prompt-pool index (refcount==1 for the whole prefill — the
        # same allocator invariant the monolithic miss admission's
        # prompt_slots ride), and the chunk cursor is a host-bounded
        # position index (< seq_len) — marking it keeps the
        # PTA190 provenance chain closed over the staging writes
        # instead of silently downgrading the prover
        absint.mark_pool_index_source(entry, "host_indices",
                                      bound=E + 1)
        absint.mark_pool_index_source(pos0, "chunk_cursor", bound=S)
        # devtel: one chunk ticked; how many decode lanes were live
        # while it did (the prefill-vs-decode occupancy split)
        _tel_add(sv, "tel_chunks",
                 layers.fill_constant([1], "int64", 1.0))
        _tel_add(sv, "tel_prefill_occupancy",
                 layers.reduce_sum(sv[f"{state_prefix}active"],
                                   keep_dim=True))
        # [C, S] chunk-position one-hot: row c selects prompt
        # position pos0+c (all-zero past seq_len — ragged tail)
        sr = layers.cast(layers.range(0, S, 1), "int64")
        cr = layers.cast(layers.range(0, C, 1), "int64")
        csel = layers.cast(
            layers.equal(sr, layers.elementwise_add(
                layers.reshape(cr, [C, 1]), pos0)), "float32")
        cselT = layers.transpose(csel, perm=[1, 0])       # [S, C]
        keep = layers.reshape(
            layers.elementwise_sub(
                layers.fill_constant([S], "float32", 1.0),
                layers.reduce_sum(csel, dim=0)), [S, 1])
        stage = [sv[f"{state_prefix}chunk_stage_a{POOL_MARK}"],
                 sv[f"{state_prefix}chunk_stage_b{POOL_MARK}"]]
        stage_kv = sv[f"{state_prefix}chunk_stage_kv{POOL_MARK}"]

        def _stage_row(pool, width):                      # [S, width]
            return layers.reshape(layers.gather(pool, entry),
                                  [S, width])

        def _chunk_of(row, width):                     # [1, C, width]
            return layers.reshape(layers.matmul(csel, row),
                                  [1, C, width])

        def _stage_merge(pool, row, chunk2d):
            # RMW the entry row: this tick's C positions replaced,
            # every other position kept — the one-hot matmul scatter
            # is exact (single nonzero per column)
            merged = layers.elementwise_add(
                layers.elementwise_mul(row, keep),
                layers.matmul(cselT, chunk2d))
            layers.masked_pool_write(
                pool, layers.unsqueeze(merged, [0]), entry,
                leading_dims=1, exclusive_via="host_indices")

        if p == 0:
            # embed the chunk's tokens + positional encoding (the
            # _embed math at chunk offsets; garbage pad tokens of a
            # ragged tail embed then scatter to nothing)
            toks = layers.data("chunk_toks", shape=[1, C],
                               dtype="int64",
                               append_batch_size=False)
            emb = layers.embedding(toks, size=[vocab, d_model],
                                   param_attr=ParamAttr(
                                       name="src_word_emb"))
            # C == 1 hits lookup_table's trailing-1 id-axis squeeze
            # ([1,1] ids give [1,D]) — restore the [1,C,D] rank
            emb = layers.reshape(emb, [1, C, d_model])
            emb = layers.scale(emb, scale=d_model ** 0.5)
            pos_tab = layers.assign(
                T._position_encoding(max(S, maxT), d_model)[:S])
            x = layers.elementwise_add(
                emb, layers.matmul(csel, pos_tab), axis=1)
            _stage_merge(stage[0], _stage_row(stage[0], d_model),
                         layers.reshape(x, [C, d_model]))
            return
        if p <= 2 * L:
            l = (p - 1) // 2
            xrow = _stage_row(stage[l % 2], d_model)
            x = _chunk_of(xrow, d_model)
            # same fused-qkv param as encoder_layer's self-attention
            qkv = layers.fc(x, 3 * d_model, num_flatten_dims=2,
                            bias_attr=False,
                            param_attr=T._attn_proj_attr(
                                f"enc{l}_self", "qkv", d_model))
            q, k, v = layers.split(qkv, 3, dim=2)
            if (p - 1) % 2 == 0:
                # kv phase: stage this chunk's K/V columns (fc is
                # per-position — chunkable; q recomputes next phase)
                _stage_merge(
                    stage_kv, _stage_row(stage_kv, 2 * d_model),
                    layers.reshape(layers.concat([k, v], axis=2),
                                   [C, 2 * d_model]))
                return
            # attention phase: C queries over the layer's FULL
            # staged K/V, then the per-position encoder tail
            kvrow = _stage_row(stage_kv, 2 * d_model)     # [S, 2D]
            kf, vf = layers.split(kvrow, 2, dim=1)
            q4 = layers.reshape(q, [0, 0, n_heads, head_dim])
            k4 = layers.reshape(kf, [1, S, n_heads, head_dim])
            v4 = layers.reshape(vf, [1, S, n_heads, head_dim])
            ctx = layers.attention(q4, k4, v4, causal=False,
                                   scale=head_dim ** -0.5,
                                   dropout_rate=0.0, layout="bthd")
            ctx = layers.reshape(ctx, [0, 0, d_model])
            attn = layers.fc(ctx, d_model, num_flatten_dims=2,
                             bias_attr=False,
                             param_attr=f"enc{l}_self_out.w")
            x1 = T._add_norm(attn, x, 0.0, True, name=f"enc{l}_a")
            ffn = T._ffn(x1, d_model, d_inner, 0.0, True,
                         name=f"enc{l}")
            x2 = T._add_norm(ffn, x1, 0.0, True, name=f"enc{l}_b")
            out_pool = stage[(l + 1) % 2]
            _stage_merge(out_pool, _stage_row(out_pool, d_model),
                         layers.reshape(x2, [C, d_model]))
            return
        # final phase: project the chunk's cross-attention K/V for
        # every decoder layer and install it into the prompt entry's
        # cross pools — the entry layout is heads_of's [H, S, Dh], so
        # the positional merge happens in a [S, H*Dh] view
        xrow = _stage_row(stage[L % 2], d_model)
        x = _chunk_of(xrow, d_model)
        for li in range(n_layers):
            kvp = layers.fc(x, 2 * d_model, num_flatten_dims=2,
                            bias_attr=False,
                            param_attr=T._attn_proj_attr(
                                f"dec{li}_cross", "kv", d_model))
            k, v = layers.split(kvp, 2, dim=2)
            for tag, val in (("k", k), ("v", v)):
                pool = sv[f"{state_prefix}cross_{tag}{li}"
                          f"{POOL_MARK}"]
                row = layers.reshape(
                    layers.transpose(layers.gather(pool, entry),
                                     perm=[0, 2, 1, 3]),
                    [S, d_model])
                merged = layers.elementwise_add(
                    layers.elementwise_mul(row, keep),
                    layers.matmul(cselT,
                                  layers.reshape(val, [C, d_model])))
                layers.masked_pool_write(
                    pool,
                    layers.transpose(
                        layers.reshape(merged,
                                       [1, S, n_heads, head_dim]),
                        perm=[0, 2, 1, 3]),
                    entry, leading_dims=1,
                    exclusive_via="host_indices")

    serves = {0: _build_serve("miss", 0)}
    for A in admit_buckets:
        if paged:
            serves[("miss", A)] = _build_serve("miss", A)
            serves[("hit", A)] = _build_serve("hit", A)
            if "radix" in admit_bodies:
                serves[("radix", A)] = _build_serve("radix", A)
        else:
            serves[A] = _build_serve("miss", A)
    if paged and cache.chunked:
        # one serve program per phase, each fused with the SAME
        # decode While as key 0 — a chunk dispatch IS a decode burst
        # with a chunk bolted on the front, so live lanes keep
        # ticking while the chunk computes (the two-tier schedule);
        # executable count grows by exactly 2*n_layers+2 programs
        for p in range(2 * n_layers + 2):
            serves[("chunked", p)] = _serve_program(
                lambda sv, _p=p: _chunk_phase_body(sv, _p))
    if spec and draft.k_options:
        # --- adaptive-k serve variants: for every non-default rung
        # of the ladder, rebuild each (admission x bucket) flavor
        # with the tick body pinned at that k. Keyed ("k", kv,
        # base_key); serve_feed_spec recurses to the base key, and
        # every variant declares the SAME slot-state specs, so the
        # host controller re-buckets lanes by pure program selection
        # — the executable count is bounded at build time
        # (|ladder|-1 extra copies of the non-chunked serve set) and
        # steady state compiles NOTHING. k decisions stay host
        # policy: no new device predicate is minted here (the burst
        # cond is the same lane_active_mask-marked one).
        base_keys = [bk for bk in serves
                     if not (isinstance(bk, tuple)
                             and bk[0] == "chunked")]
        for kv in draft.k_options:
            if kv == draft.k:
                continue
            kv_body = (_k0_body if kv == 0
                       else (lambda sv, _k=kv:
                             _spec_step_body(sv, _k)))
            for bk in base_keys:
                tier, A = (bk, 0) if bk == 0 else (
                    ("miss", bk) if isinstance(bk, int)
                    else bk)
                serves[("k", kv, bk)] = _build_serve(
                    tier, A, step_body=kv_body)

    # --- COW block copy (paged only): gather the SHARED source rows
    # and masked-write them into freshly allocated EXCLUSIVE blocks —
    # the one lowering through which a lane may diverge from a shared
    # chain (beam branching, partial-page session resume). Operating
    # on the whole [NB, BS, H, Dh] pool along dim 0 only keeps it
    # layout-oblivious under tp (the sharded heads axis is never
    # reshaped or reduced). Padded rows feed gate 0 AND dst -1 (the
    # trash row), so one fixed-shape program serves any copy count. --
    cow_prog = None
    if paged:
        cow_prog = fluid.Program()
        with fluid.program_guard(cow_prog, fluid.Program()):
            sv = _mark_ownership(
                _declare_slot_state(cow_prog.global_block, specs))
            csrc = layers.data("cow_src", shape=[rows], dtype="int64",
                               append_batch_size=False)
            cdst = layers.data("cow_dst", shape=[rows], dtype="int64",
                               append_batch_size=False)
            cgate = layers.data("cow_gate", shape=[rows],
                                dtype="float32",
                                append_batch_size=False)
            # mint-site ownership marks (analysis/absint.py seed
            # table): sources are refcount>=1 SHARED chain blocks
            # (read-legal, write-ILLEGAL — PTA192 proves no write
            # chains from them), destinations are host-fresh
            # exclusive allocations (the COW window)
            absint.mark_pool_index_source(csrc, "cow_src", bound=NB)
            absint.mark_pool_index_source(cdst, "cow_dst", bound=NB)
            for li in range(n_layers):
                for tag in ("k", "v"):
                    pool = sv[f"{state_prefix}self_{tag}{li}"
                              f"{POOL_MARK}"]
                    src_rows = layers.gather(pool, csrc)
                    layers.masked_pool_write(
                        pool, src_rows, cdst, cgate, leading_dims=1,
                        exclusive_via="cow_dst")
            _tel_add(sv, "tel_cow_blocks",
                     layers.reduce_sum(layers.cast(cgate, "int64"),
                                       keep_dim=True))

    # --- probe step (paged, non-spec): one decode tick that ALSO
    # publishes every lane's full softmax row to probe_probs — the
    # paged beam decoder's expansion oracle (host selects tokens,
    # device owns KV; under permanent teacher forcing the tick never
    # writes tok_buf or latches fin) ---------------------------------
    probe_prog = None
    if paged and not spec:
        probe_prog = fluid.Program()
        with fluid.program_guard(probe_prog, fluid.Program()):
            _step_body(_mark_ownership(_declare_slot_state(
                probe_prog.global_block, specs)), probe=True)

    state = {"tok_buf": f"{state_prefix}tok_buf",
             "step": f"{state_prefix}step",
             "finished": f"{state_prefix}finished",
             "active": f"{state_prefix}active"}
    if paged:
        state["block_tab"] = f"{state_prefix}block_tab"
        state["prompt_ref"] = f"{state_prefix}prompt_ref"
        state["prefill_until"] = f"{state_prefix}prefill_until"
        if probe_prog is not None:
            state["probe_probs"] = f"{state_prefix}probe_probs"
    if needs_seeds:
        state["seed"] = f"{state_prefix}seed"
    if spec:
        for c in ("spec_proposed", "spec_accepted", "spec_emitted",
                  "spec_draft_steps", "spec_target_steps",
                  "spec_lane_accepted", "spec_lane_ticks"):
            state[c] = f"{state_prefix}{c}"
        if draft.k_options:
            state.update(devtel.spec_k_state_entries(
                state_prefix, draft.k_options))
    # devtel counters join the state map (and therefore the PTA150
    # counter-presence sweep) under their logical names
    state.update(devtel.state_entries(state_prefix, paged))
    bundle = DecodeStepBundle(prefills, step_prog, serves, startup,
                              state, n_slots, seq_len, maxT, start_id,
                              end_id, cache=cache,
                              hit_prefills=hit_prefills,
                              sampling=sampling, draft=draft,
                              cow=cow_prog, probe=probe_prog)
    bundle._state_specs = {
        n: (shape, dt) for n, (shape, dt) in specs.items()}
    if sharding is not None and sharding.enabled:
        _apply_tp_sharding(bundle, sharding, n_layers)
    return bundle


# ---------------------------------------------------------------------------
# Beam front (the last decode loop folded in from transformer.py —
# every decode capability now lives in this module).
# ---------------------------------------------------------------------------
def build_beam_decode_program(seq_len=16, max_out_len=16, d_model=64,
                              n_heads=4, n_layers=2, d_inner=128,
                              vocab=1000, start_id=0, end_id=1,
                              beam_size=4, batch_size=1):
    """Batched beam-search generation (reference
    tests/unittests/dist_transformer.py:1523 beam_search inside
    fast_decode). Beams ride the batch axis at static
    [batch*beam, maxT] shapes (batch-major blocks of beam rows, the
    beam_search op's row layout): every step runs the causally-masked
    decoder over all rows, expands per-source with the beam_search op
    (accumulated log-probs, EOS freezing), reorders each hypothesis'
    token history by absolute parent_idx, and backtracks with
    beam_search_decode.

    Weight sharing: the explicit enc{i}_*/dec{i}_*/logits.w names.
    Returns (program, startup, feeds, (sentence_ids
    [T, batch*beam], sentence_scores [batch*beam])).
    """
    import paddle_tpu as fluid

    from . import transformer as T

    maxT = max_out_len
    rows = batch_size * beam_size
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        # static-batch program so build-time probes agree with the
        # concrete [rows, ...] vars downstream
        src = layers.data("src_ids", shape=[batch_size, seq_len],
                          dtype="int64", append_batch_size=False)
        enc1 = T._embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                        True, "src_word_emb")
        for li in range(n_layers):
            enc1 = T.encoder_layer(enc1, d_model, n_heads, d_inner,
                                   0.0, is_test=True, name=f"enc{li}")
        # repeat each source's encoding beam_size times consecutively
        # ([B,S,D] -> [B,beam,S,D] -> [B*beam,S,D], batch-major rows)
        enc = layers.reshape(
            layers.expand(layers.unsqueeze(enc1, [1]),
                          [1, beam_size, 1, 1]),
            [rows, seq_len, d_model])

        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        # per-hypothesis token history [rows, maxT], GO at position 0
        tgt_buf = layers.assign(layers.fill_constant(
            [rows, maxT], "int64", 0.0))
        if start_id:
            start_col = layers.cast(
                layers.equal(positions,
                             layers.fill_constant([1], "int64", 0.0)),
                "int64")
            tgt_buf = layers.assign(layers.elementwise_add(
                tgt_buf, layers.cast(
                    layers.scale(start_col, scale=float(start_id)),
                    "int64")))
        pre_ids = layers.assign(layers.fill_constant(
            [rows, 1], "int64", float(start_id)))
        # ONE live beam per source at step 0 (the reference's LoD
        # single-seed): identical rows with equal scores would make
        # per-block top-k pick beam_size copies of the same argmax and
        # the beams would never diverge (degenerate greedy)
        pre_scores = layers.assign(np.where(
            np.arange(rows) % beam_size == 0, 0.0,
            -1e9).astype("float32").reshape(rows, 1))
        # step buffers for the backtrack [maxT, rows, 1]
        ids_buf = layers.assign(layers.fill_constant(
            [maxT, rows, 1], "int64", float(end_id)))
        scores_buf = layers.assign(layers.fill_constant(
            [maxT, rows, 1], "float32", 0.0))
        parents_buf = layers.assign(layers.fill_constant(
            [maxT, rows, 1], "int64", 0.0))
        zero = layers.fill_constant([1], "int64", 0)
        ids_buf = layers.assign(layers.scatter(
            ids_buf, zero, layers.reshape(pre_ids, [1, rows, 1])))

        counter = layers.fill_constant([1], "int64", 0)
        limit = layers.fill_constant([1], "int64", float(maxT - 1))
        cond = layers.less_than(counter, limit)
        w = layers.While(cond)
        with w.block():
            dec = T._embed(tgt_buf, vocab, d_model,
                           max(seq_len, maxT), 0.0, True,
                           "tgt_word_emb")
            for li in range(n_layers):
                dec = T.decoder_layer(dec, enc, d_model, n_heads,
                                      d_inner, 0.0, is_test=True,
                                      name=f"dec{li}")
            logits_v = step_logits(dec, positions, counter,
                                   vocab)  # [rows, V]
            probs = layers.softmax(logits_v)  # [rows, V]
            topk_scores, topk_ids = layers.topk(
                probs, min(2 * beam_size, vocab))
            acc = layers.elementwise_add(layers.log(topk_scores),
                                         pre_scores)
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids, pre_scores, topk_ids, acc,
                beam_size=beam_size, end_id=end_id,
                return_parent_idx=True)
            parent_flat = layers.reshape(parent, shape=[rows])
            # each surviving hypothesis inherits its parent's history
            layers.assign(layers.gather(tgt_buf, parent_flat),
                          output=tgt_buf)
            layers.increment(counter, 1)
            next_mask = layers.cast(layers.equal(positions, counter),
                                    "int64")
            keep = layers.elementwise_sub(
                layers.fill_constant([maxT], "int64", 1.0), next_mask)
            layers.assign(layers.elementwise_add(
                layers.elementwise_mul(tgt_buf, keep),
                layers.elementwise_mul(
                    layers.reshape(sel_ids, [rows, 1]),
                    next_mask)), output=tgt_buf)
            layers.assign(layers.scatter(
                ids_buf, counter,
                layers.reshape(sel_ids, [1, rows, 1])),
                output=ids_buf)
            layers.assign(layers.scatter(
                scores_buf, counter,
                layers.reshape(sel_scores, [1, rows, 1])),
                output=scores_buf)
            layers.assign(layers.scatter(
                parents_buf, counter,
                layers.reshape(parent, [1, rows, 1])),
                output=parents_buf)
            layers.assign(layers.reshape(sel_ids, [rows, 1]),
                          output=pre_ids)
            layers.assign(layers.reshape(sel_scores, [rows, 1]),
                          output=pre_scores)
            layers.less_than(counter, limit, cond=cond)
        out_ids, out_scores = layers.beam_search_decode(
            ids_buf, scores_buf, beam_size=beam_size, end_id=end_id,
            parents=parents_buf)
    return main, startup, ["src_ids"], (out_ids, out_scores)


# ---------------------------------------------------------------------------
# Host-side allocation policy (plain Python; the device only sees the
# tables the scheduler writes into the scope).
# ---------------------------------------------------------------------------
class ServingUnavailable(RuntimeError):
    """Base of the serving-layer rejection taxonomy: every named
    condition under which the front door cannot take (or keep) a
    request derives from this ONE class, carrying the machine-readable
    retry contract — ``retryable`` (may the caller resubmit the same
    request and expect a different outcome?) and ``retry_after_ms``
    (earliest point a retry is worth attempting, ``None`` = no
    estimate). Retry logic anywhere above (runtime Router, clients)
    dispatches on ``isinstance`` + these attributes ONLY — never on
    message text (the r20 taxonomy contract; message-substring
    matching is what this base exists to delete).

    Subclasses: ``BlockPoolExhausted``/``ServerQuiesced``/
    ``ServerClosed`` (transient, retryable), ``AdmissionInfeasible``
    (config can never admit — not retryable), the Router's
    ``AdmissionError`` family including the deadline-shed rejection
    (retryability depends on the reason). Reference counterpart: none
    — the reference's serving errors are bare PADDLE_ENFORCE strings
    (inference/api/analysis_predictor.cc); a typed retry contract is
    the multi-tenant front-door tier this layer adds."""

    retryable = False
    retry_after_ms = None


class BlockPoolExhausted(ServingUnavailable):
    """The shared KV block pool (or the prompt-entry pool) cannot
    satisfy an allocation AND nothing in flight can ever free one —
    a NAMED, RETRYABLE error (``retryable=True``): the caller may
    resubmit once other requests retire, or against a server with a
    larger pool. Raised instead of hanging the scheduler (the r13
    acceptance contract); transient pressure is handled by queueing/
    pausing, never by this error."""

    retryable = True
    retry_after_ms = 50.0


class AdmissionInfeasible(ServingUnavailable):
    """The serving CONFIGURATION (not transient load) can never admit
    this request: the liveness capacity model
    (analysis/liveness.py ``session_feasibility``, validated against
    the exhaustive protomodel explorer) proves steady-state demand
    exceeds a static pool — e.g. more distinct session prompts than
    ``n_prompt_entries``, each pinning an entry for its session
    lifetime. NAMED and NOT retryable (``retryable=False``): unlike
    ``BlockPoolExhausted``, waiting cannot help — pinned entries are
    unevictable until a session closes, so the preflight raises up
    front instead of letting admissions wedge silently at runtime.

    Reference counterpart: none — the reference admits until OOM
    (runtime PADDLE_ENFORCE); a provably-infeasible-config error is
    the capacity-model tier this layer adds."""

    retryable = False


class BlockLifetimeError(ValueError):
    """A host-allocator call violated the per-block lifetime lattice
    ``free → exclusive(lane) → shared(refcount>1) → freed``: freeing
    an unallocated or already-freed block, or releasing a zero-ref
    prompt entry. NAMED (and a ValueError subclass for callers that
    caught the old bare error) so the scheduler fails loudly at the
    bad transition instead of silently corrupting the free list —
    the next alloc would hand one block to TWO lanes and break the
    very disjointness invariant the ownership prover (PTA191)
    assumes. The full automaton is property-tested in
    tests/test_block_pool_model.py."""


class HostBlockPool:
    """Free-list over the ``n_blocks`` shared self-KV blocks, run as
    an explicit TYPESTATE machine riding per-block refcounts:
    ``free -> exclusive (refcount==1, owned by one lane) -> shared
    (refcount>1, read-only radix prefix) -> free``. This is the host
    half of the lane-exclusivity story the ownership prover leans on
    — its alloc-disjoint invariant is the NAMED assumption
    (``HostBlockPool.alloc-disjoint``, analysis/absint.py ownership
    seed table) under which PTA191 proves distinct lanes' pool
    writes hit disjoint rows: every block a lane can WRITE (the
    write-reachable suffix of its table) is exclusive to it; shared
    blocks may appear in many tables but only in the read-only
    prefix below ``resume_step`` (PTA192's read-only-while-shared is
    the device half, the host half is ``writable()`` here). Invalid
    transitions raise ``BlockLifetimeError`` instead of corrupting
    the free list (a double-freed block would be handed to two
    lanes)."""

    def __init__(self, n_blocks: int):
        self.n_blocks = int(n_blocks)
        self._free = list(range(self.n_blocks))
        self._state = ["free"] * self.n_blocks
        self._refs = [0] * self.n_blocks

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        b = self._free.pop()
        self._state[b] = "exclusive"
        self._refs[b] = 1
        return b

    def free(self, blocks):
        """Strict single-owner free: legal ONLY from the exclusive
        (refcount==1) typestate — the legacy lane-release path.
        Radix-aware callers holding one ref among several use
        ``decref`` instead; routing a possibly-shared block through
        here raises rather than yanking KV other lanes attend to."""
        blocks = list(blocks)
        seen = set()
        for b in blocks:
            if not 0 <= b < self.n_blocks:
                raise BlockLifetimeError(
                    f"free of block {b} outside the pool "
                    f"[0, {self.n_blocks})")
            if self._state[b] != "exclusive" or b in seen:
                raise BlockLifetimeError(
                    f"free of block {b} in typestate "
                    f"{'freed-in-this-call' if b in seen else self._state[b]!r} "
                    f"(legal only from 'exclusive'): double-free/"
                    f"unallocated/shared free would hand one block "
                    f"to two lanes")
            seen.add(b)
        for b in blocks:
            self._state[b] = "free"
            self._refs[b] = 0
            self._free.append(b)

    # --- refcount surface (the radix tree + COW path) ----------------
    def incref(self, block: int) -> int:
        """A new reader adopts the block (radix-tree node, extra lane
        mapping it read-only, COW source pin). refcount 1 -> 2 is the
        exclusive -> shared transition."""
        if not 0 <= block < self.n_blocks:
            raise BlockLifetimeError(
                f"incref of block {block} outside the pool "
                f"[0, {self.n_blocks})")
        if self._refs[block] <= 0:
            raise BlockLifetimeError(
                f"incref of block {block} in typestate "
                f"{self._state[block]!r} (refcount 0): a freed block "
                f"may be re-handed to another lane at any alloc")
        self._refs[block] += 1
        self._state[block] = "shared"
        return self._refs[block]

    def decref(self, block: int) -> int:
        """Drop one reference; at refcount 0 the block returns to the
        free list (the shared -> exclusive -> free unwinding; a
        decref from refcount 1 IS the radix-aware free)."""
        if not 0 <= block < self.n_blocks:
            raise BlockLifetimeError(
                f"decref of block {block} outside the pool "
                f"[0, {self.n_blocks})")
        if self._refs[block] <= 0:
            raise BlockLifetimeError(
                f"decref of block {block} at refcount "
                f"{self._refs[block]}: refcounts never go negative — "
                f"a double decref would free KV another reader still "
                f"attends to")
        self._refs[block] -= 1
        if self._refs[block] == 0:
            self._state[block] = "free"
            self._free.append(block)
        elif self._refs[block] == 1:
            self._state[block] = "exclusive"
        return self._refs[block]

    def refcount(self, block: int) -> int:
        return self._refs[block]

    def writable(self, block: int) -> bool:
        """True while a device write into the block is legal:
        refcount == 1 (single owner). A lane's first write into a
        SHARED block must COW — copy into a fresh exclusive block,
        then decref the shared source — never write through the
        shared path (checker PTA192's host half)."""
        return self._refs[block] == 1

    def typestate(self, block: int) -> str:
        return self._state[block]

    def live_blocks(self) -> set:
        return {b for b, s in enumerate(self._state)
                if s != "free"}

    def shared_blocks(self) -> set:
        return {b for b, s in enumerate(self._state)
                if s == "shared"}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.n_blocks - len(self._free)


class PromptPrefixCache:
    """Refcounted exact-prompt cache over the cross-KV entry pool,
    with block-hash-chain partial detection (the SGLang/RadixAttention
    shape at whole-prompt granularity: this framework's encoder is
    BIDIRECTIONAL, so a cross-KV column depends on the WHOLE prompt
    and only a full-content match may reuse an entry; a leading-chunk
    match is reported as the ``partial`` tier — re-prefilled like a
    miss, and counted as a copy-on-write materialization — which a
    causal-encoder model could upgrade to true radix reuse).

    Entries are pinned while any lane references them (``refs > 0``);
    unpinned entries stay cached LRU and are evicted only when a miss
    needs a slot. Counters feed the block-pool observability gauges
    (prefix_hits/misses/partials=cow_copies, evictions)."""

    def __init__(self, n_entries: int, chunk_tokens: int):
        self.n_entries = int(n_entries)
        self.chunk = max(1, int(chunk_tokens))
        self._free = list(range(self.n_entries))
        self._by_prompt: Dict[tuple, int] = {}   # prompt -> entry
        self._entry_prompt: Dict[int, tuple] = {}
        self._refs: Dict[int, int] = {}
        self._lru: "Dict[tuple, None]" = {}      # insertion-ordered
        self._heads: Dict[tuple, int] = {}       # first chunk -> count
        self.hits = 0
        self.misses = 0
        self.partials = 0       # exposed as cow_copies
        self.evictions = 0

    def _head(self, prompt: tuple) -> tuple:
        return prompt[:self.chunk]

    def lookup(self, prompt: tuple) -> Tuple[str, Optional[int]]:
        """('hit', entry) on a full-content match, ('partial', None)
        when only a leading chunk matches a cached prompt, else
        ('miss', None). Pure lookup — no counters, no refcounts (the
        scheduler may probe the queue head every cycle)."""
        entry = self._by_prompt.get(prompt)
        if entry is not None:
            return "hit", entry
        if self._heads.get(self._head(prompt)):
            return "partial", None
        return "miss", None

    def acquire_hit(self, prompt: tuple) -> int:
        entry = self._by_prompt[prompt]
        self._refs[entry] = self._refs.get(entry, 0) + 1
        self._lru.pop(prompt, None)
        self._lru[prompt] = None
        self.hits += 1
        return entry

    def acquire_fresh(self, prompt: tuple,
                      partial: bool = False) -> Optional[int]:
        """Entry for a cold prompt: a free slot, else the LRU
        UNPINNED entry (evicted). None when every entry is pinned —
        the caller backpressures (or, with nothing in flight, raises
        BlockPoolExhausted)."""
        if self._free:
            entry = self._free.pop()
        else:
            victim = next((p for p in self._lru
                           if self._refs.get(self._by_prompt[p],
                                             0) == 0), None)
            if victim is None:
                return None
            entry = self._by_prompt.pop(victim)
            self._lru.pop(victim, None)
            self._entry_prompt.pop(entry, None)
            head = self._head(victim)
            self._heads[head] -= 1
            if not self._heads[head]:
                del self._heads[head]
            self.evictions += 1
        self._by_prompt[prompt] = entry
        self._entry_prompt[entry] = prompt
        self._refs[entry] = 1
        self._lru[prompt] = None
        self._heads[self._head(prompt)] = \
            self._heads.get(self._head(prompt), 0) + 1
        if partial:
            self.partials += 1
        else:
            self.misses += 1
        return entry

    def release(self, entry: int):
        refs = self._refs.get(entry, 0)
        if refs <= 0:
            raise BlockLifetimeError(
                f"release of prompt entry {entry} at refcount "
                f"{refs}: refcounts are monotone within a lifetime "
                f"(acquire+/release-) and never go negative — a "
                f"double release would unpin an entry another lane "
                f"still attends to")
        self._refs[entry] = refs - 1

    def invalidate(self, entry: int):
        """Forget an UNPINNED entry's prompt mapping and return the
        slot to the free list — for an ABANDONED part-written prefill
        (a chunked-prefill job whose dispatch failed mid-fill): the
        prompt must never again be looked up as a hit against stale
        cross-KV. Raises while any lane still references the entry
        (typestate: only a free entry may be forgotten)."""
        if self._refs.get(entry, 0) > 0:
            raise BlockLifetimeError(
                f"invalidate of prompt entry {entry} at refcount "
                f"{self._refs[entry]}: a referenced entry is still "
                f"attended to — release every ref first")
        prompt = self._entry_prompt.pop(entry, None)
        if prompt is None:
            return
        del self._by_prompt[prompt]
        self._lru.pop(prompt, None)
        head = self._head(prompt)
        self._heads[head] -= 1
        if not self._heads[head]:
            del self._heads[head]
        self._refs.pop(entry, None)
        self._free.append(entry)

    # --- the refcount typestate surface (the COW contract PTA192
    # checks the device half of): free -> exclusive (refcount==1) ->
    # shared (refcount>1) -> back; writes to an entry's KV are only
    # legal while it is EXCLUSIVE — acquire_fresh's refcount==1
    # window is when admission prefill writes happen, and the
    # ``PromptPrefixCache.fresh-exclusive`` assumption PTA191 names
    # is exactly that window's guarantee. ----------------------------
    def refcount(self, entry: int) -> int:
        return self._refs.get(entry, 0)

    def is_shared(self, entry: int) -> bool:
        return self.refcount(entry) > 1

    def writable(self, entry: int) -> bool:
        """True while a write to the entry's pooled KV is legal:
        refcount <= 1 (nobody else attends to it). A COW lowering
        must check this (or copy to a fresh entry) before mutating."""
        return self.refcount(entry) <= 1

    def typestate(self, entry: int) -> str:
        refs = self.refcount(entry)
        if refs == 0:
            return "free"
        return "exclusive" if refs == 1 else "shared"

    @property
    def in_use(self) -> int:
        return sum(1 for r in self._refs.values() if r > 0)


class _RadixNode:
    __slots__ = ("chunk", "block", "children", "parent")

    def __init__(self, chunk, block, parent):
        self.chunk = chunk        # the BS-token tuple this edge spells
        self.block = block        # pool block holding its self-KV
        self.children = {}        # chunk tuple -> _RadixNode
        self.parent = parent


class RadixBlockTree:
    """Host-side radix tree over decoded-token -> self-KV block
    chains (the SGLang/RadixAttention longest-shared-prefix shape,
    PAPERS.md, on vLLM-style block tables — reference counterpart:
    none; the reference framework's fast_decode
    (tests/unittests/dist_transformer.py:1498) holds per-request
    dense caches with nothing shareable).

    Granularity is one FULL block (``block_size`` tokens): a node is
    a block whose KV is fully determined by the root prompt plus the
    token chunks spelling the path to it. Roots are keyed by the
    PROMPT CONTENT tuple — this framework's encoder is bidirectional,
    so every self-KV row also attends cross-attention values derived
    from the whole prompt, and chains are shareable only between
    requests with the SAME prompt (the cross-KV entry the
    PromptPrefixCache already dedupes).

    Refcount protocol (HostBlockPool): the tree holds ONE ref per
    adopted node (``incref`` at insert); every lane mapping a chain
    read-only holds one ref per block (``acquire``/``release``). A
    node whose block is at refcount 1 is tree-only and evictable —
    ``evict`` drops such LEAF nodes (never an interior node: its
    children's KV transitively depends on it), which is exactly the
    "eviction only unpins refcount-0 subtrees" invariant
    tests/test_block_pool_model.py property-checks."""

    def __init__(self, pool: "HostBlockPool", block_size: int):
        self.pool = pool
        self.block_size = max(1, int(block_size))
        self._roots: Dict[tuple, _RadixNode] = {}
        self.inserts = 0
        self.adoptions = 0
        self.hit_blocks = 0
        self.evicted_blocks = 0

    def _chunks(self, tokens):
        toks = tuple(int(t) for t in tokens)
        bs = self.block_size
        return [toks[i:i + bs] for i in
                range(0, len(toks) - len(toks) % bs, bs)]

    def _walk(self, prompt, tokens):
        """Longest-prefix walk: (matched nodes, first divergent chunk
        index)."""
        node = self._roots.get(tuple(int(t) for t in prompt))
        path = []
        if node is None:
            return path
        for chunk in self._chunks(tokens):
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            path.append(nxt)
            node = nxt
        return path

    def match(self, prompt, tokens) -> int:
        """Longest shared block-prefix depth (in BLOCKS) for this
        (prompt, decoded-token) pair. Pure probe — no refcounts."""
        return len(self._walk(prompt, tokens))

    def acquire(self, prompt, tokens, max_blocks=None):
        """Map the longest shared prefix read-only into a lane: one
        ``incref`` per matched block (the lane's refs — released
        with ``release``). Returns the block-id list, shallowest
        first."""
        path = self._walk(prompt, tokens)
        if max_blocks is not None:
            path = path[:max_blocks]
        blocks = [n.block for n in path]
        for b in blocks:
            self.pool.incref(b)
        self.hit_blocks += len(blocks)
        return blocks

    def release(self, blocks):
        """Drop a lane's refs on a shared chain (reverse order so a
        block freed at refcount 0 never outlives a deeper block that
        depends on it)."""
        for b in reversed(list(blocks)):
            self.pool.decref(b)

    def insert(self, prompt, tokens, blocks) -> int:
        """Adopt a finished lane's FULL-block chain: walk the chunks;
        where a node already exists the existing block wins (the
        lane's duplicate stays lane-owned — the caller releases it
        normally); where it doesn't, the tree adopts the lane's block
        with its OWN incref (the lane still releases its ref).
        Returns the number of newly adopted blocks."""
        key = tuple(int(t) for t in prompt)
        chunks = self._chunks(tokens)
        if not chunks:
            return 0
        blocks = list(blocks)
        if len(blocks) < len(chunks):
            raise BlockLifetimeError(
                f"radix insert of {len(chunks)} full chunks backed "
                f"by only {len(blocks)} blocks: a node without its "
                f"KV block would serve garbage to every later hit")
        root = self._roots.get(key)
        if root is None:
            root = self._roots[key] = _RadixNode(None, None, None)
        node, adopted = root, 0
        for chunk, block in zip(chunks, blocks):
            nxt = node.children.get(chunk)
            if nxt is None:
                self.pool.incref(block)
                nxt = _RadixNode(chunk, block, node)
                node.children[chunk] = nxt
                adopted += 1
            node = nxt
        self.inserts += 1
        self.adoptions += adopted
        return adopted

    def evict(self, need: int) -> int:
        """Free >= ``need`` blocks by unpinning tree-only (refcount
        1) LEAF nodes, deepest first. Returns how many were freed;
        pinned subtrees (any lane ref anywhere below) are never
        touched."""
        freed = 0
        while freed < need:
            victim = None
            for root in self._roots.values():
                stack = [(c, 1) for c in root.children.values()]
                best = None
                while stack:
                    n, d = stack.pop()
                    if n.children:
                        stack.extend((c, d + 1)
                                     for c in n.children.values())
                    elif self.pool.refcount(n.block) == 1:
                        if best is None or d > best[1]:
                            best = (n, d)
                if best is not None and (
                        victim is None or best[1] > victim[1]):
                    victim = best
            if victim is None:
                break
            node = victim[0]
            del node.parent.children[node.chunk]
            self.pool.decref(node.block)
            freed += 1
            self.evicted_blocks += 1
        for key in [k for k, r in self._roots.items()
                    if not r.children]:
            del self._roots[key]
        return freed

    def tree_blocks(self) -> set:
        """Every block currently adopted by a node (the tree's own
        refs) — the property tests' overlap oracle."""
        out = set()
        for root in self._roots.values():
            stack = list(root.children.values())
            while stack:
                n = stack.pop()
                out.add(n.block)
                stack.extend(n.children.values())
        return out

    @property
    def n_nodes(self) -> int:
        return len(self.tree_blocks())


__all__ = ["CacheConfig", "SamplingConfig", "DraftConfig",
           "ShardingConfig", "DecodeStepBundle", "DECODE_STEPS_VAR",
           "POOL_MARK", "LANE_AXIS",
           "tp_param_placements", "annotate_sharded_program",
           "place_sharded_bundle", "place_sharded_program",
           "ServingUnavailable", "BlockPoolExhausted",
           "BlockLifetimeError", "AdmissionInfeasible",
           "HostBlockPool", "RadixBlockTree",
           "PromptPrefixCache", "build_greedy_decode_program",
           "build_incremental_decode_program",
           "build_decode_step_program", "build_beam_decode_program",
           "cached_decoder_step",
           "step_logits", "init_token_buffer", "emit_token_step",
           "heads_of"]
