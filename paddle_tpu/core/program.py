"""Program representation: the program-as-data capability surface.

TPU-native analogue of the reference's ProgramDesc/BlockDesc/OpDesc/VarDesc
(reference: paddle/fluid/framework/framework.proto:24-186 and
python/paddle/fluid/framework.py:327,877,1339,2660). The reference keeps a
protobuf program that C++ executors interpret op-by-op; here the Program is
the *trace source*: the Executor lowers a whole Block to one XLA computation
via jax.jit, so the per-op host dispatch loop of the reference
(framework/executor.cc:377) disappears at run time.

The structure is intentionally serializable (to_dict/from_dict) to support
save_inference_model-style export (reference python/paddle/fluid/io.py:865).
"""
from __future__ import annotations

import contextlib
import copy
import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .types import DataType, VarType, as_datatype


class Variable:
    """A named slot in a Block (reference framework.py:327).

    Holds static metadata only (shape/dtype/lod_level/persistable); runtime
    values live in a Scope. shape may contain -1 for the batch dimension.
    """

    def __init__(self, block, name, shape=None, dtype=None,
                 lod_level=0, persistable=False, stop_gradient=False,
                 trainable=True, type=VarType.LOD_TENSOR, initializer=None,
                 is_data=False, need_check_feed=False, regularizer=None,
                 error_clip=None, do_model_average=False):
        self.block = block
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = as_datatype(dtype) if dtype is not None else None
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.trainable = trainable
        self.type = type
        self.initializer = initializer
        self.is_data = is_data
        self.regularizer = regularizer
        self.error_clip = error_clip
        self.do_model_average = do_model_average

    # --- fluid-compatible sugar -------------------------------------------
    def astype(self, dtype):
        from .. import layers

        return layers.cast(self, dtype)

    def _binary(self, other, op, reverse=False):
        from .. import layers

        if not isinstance(other, Variable):
            other_np = np.asarray(other, dtype=self.dtype.value
                                  if self.dtype else "float32")
            other = layers.fill_constant(
                shape=list(other_np.shape) or [1],
                dtype=self.dtype or "float32", value=float(other_np))
        a, b = (other, self) if reverse else (self, other)
        return getattr(layers, op)(a, b)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    def __radd__(self, o):
        return self._binary(o, "elementwise_add", True)

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    def __rmul__(self, o):
        return self._binary(o, "elementwise_mul", True)

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", True)

    def __neg__(self):
        from .. import layers

        return layers.scale(self, scale=-1.0)

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype.value if self.dtype else None}, "
                f"persistable={self.persistable})")

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype.value if self.dtype else None,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "trainable": self.trainable,
            "type": self.type.value,
            "is_data": self.is_data,
        }

    @staticmethod
    def from_dict(block, d):
        return Variable(
            block, d["name"], shape=d["shape"], dtype=d["dtype"],
            lod_level=d.get("lod_level", 0),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            trainable=d.get("trainable", True),
            type=VarType(d.get("type", "lod_tensor")),
            is_data=d.get("is_data", False))


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class Operator:
    """One op invocation (reference framework.py:877 / op_desc.h).

    inputs/outputs map slot name -> list of variable names. attrs is a plain
    dict (ints/floats/strings/bools/lists, or a Block for control-flow ops).
    """

    def __init__(self, block, type: str, inputs: Dict[str, List[str]],
                 outputs: Dict[str, List[str]], attrs: Optional[Dict] = None):
        # structural per-op id: the PRNG salt for ops that sample
        # (dropout, nce, ...). Derived from (block idx, op position) so
        # identical program builds get identical salts (seeded
        # reproducibility), and the grad op can re-derive the forward's
        # exact noise via its __fwd_op__ attr.
        blk_idx = getattr(block, "idx", 0) or 0
        n_ops = len(getattr(block, "ops", ()) or ())
        self._uid = blk_idx * 100003 + n_ops
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in inputs.items()}
        self.outputs = {k: list(v) for k, v in outputs.items()}
        self.attrs = dict(attrs or {})

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for v in self.inputs.values() for n in v]

    @property
    def output_arg_names(self):
        return [n for v in self.outputs.values() for n in v]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        return f"Op({self.type}: {self.inputs} -> {self.outputs})"

    def to_dict(self):
        attrs = {}
        for k, v in self.attrs.items():
            if k.startswith("__"):
                continue  # runtime-only attrs (e.g. grad-op fwd link)
            if isinstance(v, Block):
                attrs[k] = {"__block__": v.idx}
            elif isinstance(v, np.ndarray):
                attrs[k] = {"__ndarray__": v.reshape(-1).tolist(),
                            "dtype": str(v.dtype),
                            "shape": list(v.shape)}
            else:
                attrs[k] = v
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": attrs,
                # structural uid: sampling ops derive their PRNG salt
                # from it, and recompute clones copy it so re-tossed
                # noise matches (backward.py _emit_recompute)
                "uid": self._uid}

    @staticmethod
    def from_dict(block, d, program):
        attrs = {}
        for k, v in d["attrs"].items():
            if isinstance(v, dict) and "__block__" in v:
                attrs[k] = program.blocks[v["__block__"]]
            elif isinstance(v, dict) and "__ndarray__" in v:
                arr = np.asarray(v["__ndarray__"], dtype=v["dtype"])
                if "shape" in v:
                    arr = arr.reshape(v["shape"])
                attrs[k] = arr
            else:
                attrs[k] = v
        op = Operator(block, d["type"], d["inputs"], d["outputs"], attrs)
        if "uid" in d:
            op._uid = d["uid"]
        return op


class Block:
    """A sequence of ops + a var table (reference framework.py:1339)."""

    def __init__(self, program, idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def create_var(self, name=None, **kwargs):
        if name is None:
            from ..unique_name import generate

            name = generate("tmp")
        if name in self.vars:
            return self.vars[name]
        var = Variable(self, name, **kwargs)
        self.vars[name] = var
        return var

    def create_parameter(self, name, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        var = self.create_var(name=name, shape=shape, dtype=dtype, **kwargs)
        self.program._parameters.setdefault(name, var)
        return var

    def var(self, name) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"Variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        inputs = _normalize_io(inputs)
        outputs = _normalize_io(outputs)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._version += 1
        # infer shapes for outputs eagerly so later layers can read .shape
        from .registry import infer_shape_for_op

        infer_shape_for_op(op, self)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        inputs = _normalize_io(inputs)
        outputs = _normalize_io(outputs)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._version += 1
        from .registry import infer_shape_for_op

        infer_shape_for_op(op, self)
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        inputs = _normalize_io(inputs)
        outputs = _normalize_io(outputs)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._version += 1
        from .registry import infer_shape_for_op

        infer_shape_for_op(op, self)
        return op

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


def _normalize_io(io) -> Dict[str, List[str]]:
    """Accept {slot: Variable | name | list of either} and normalize."""
    out: Dict[str, List[str]] = {}
    if not io:
        return out
    for slot, val in io.items():
        if val is None:
            continue
        if not isinstance(val, (list, tuple)):
            val = [val]
        names = []
        for v in val:
            if isinstance(v, Variable):
                names.append(v.name)
            elif isinstance(v, str):
                names.append(v)
            else:
                raise TypeError(f"bad io entry for slot {slot}: {v!r}")
        if names:
            out[slot] = names
    return out


_program_uid_counter = itertools.count()


class Program:
    """A whole trainable/executable program (reference framework.py:2660)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self._parameters: Dict[str, Variable] = {}
        self._version = 0
        # process-unique identity for executable cache keys: id() is
        # unsound (a GC'd Program's address can be reused by a new
        # Program whose _version also starts at 0)
        self._uid = next(_program_uid_counter)
        self._seed = None
        self.op_role_vars: List[str] = []

    # --- structure ---------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None) -> Block:
        if parent_idx is None:
            parent_idx = self.current_block_idx
        blk = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        return blk

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def all_parameters(self) -> List[Variable]:
        return list(self._parameters.values())

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = seed

    # --- transforms --------------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep structural copy (reference Program.clone framework.py:3059).

        for_test=True switches is_test-style attrs (dropout/batch_norm) to
        inference behaviour, mirroring the reference's test-program
        cloning -- and additionally prunes backward/optimize-role ops, so
        cloning AFTER minimize() still yields a pure eval program (the
        reference requires cloning before append_backward).
        """
        p = Program()
        p.blocks = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            p.blocks.append(nb)
        for blk, nb in zip(self.blocks, p.blocks):
            for name, var in blk.vars.items():
                nv = copy.copy(var)
                nv.block = nb
                nb.vars[name] = nv
            for op in blk.ops:
                if for_test and op.attrs.get("op_role") in (
                        "backward", "optimize", "lr_sched"):
                    continue
                attrs = dict(op.attrs)
                if for_test and "is_test" in attrs:
                    attrs["is_test"] = True
                for k, v in attrs.items():
                    if isinstance(v, Block):
                        attrs[k] = p.blocks[v.idx]
                nop = Operator(nb, op.type, op.inputs, op.outputs,
                               attrs)
                nop._uid = op._uid  # keep PRNG salts stable (see to_dict)
                nb.ops.append(nop)
        p._parameters = {n: p.global_block.vars[n]
                         for n in self._parameters if n in p.global_block.vars}
        p.current_block_idx = 0
        p._version = self._version
        p._seed = self._seed
        # analysis-layer program attrs ride the clone like the var-
        # level sharding annotations (copy.copy above) already do:
        # an eval/serving clone keeps its mesh (per-device memory
        # plans, PTA160/161 axis naming) and its OOM-gate budget
        for attr in ("_mesh_config", "_device_memory_budget"):
            if hasattr(self, attr):
                setattr(p, attr, getattr(self, attr))
        return p

    def _prune(self, targets: Sequence[str]) -> "Program":
        """Keep only ops needed to compute target vars (reference
        Program._prune, used by save_inference_model io.py:865)."""
        p = self.clone()
        blk = p.global_block
        needed = set(targets)
        kept = []
        for op in reversed(blk.ops):
            if set(op.output_arg_names) & needed:
                kept.append(op)
                needed.update(op.input_arg_names)
        blk.ops = list(reversed(kept))
        used = set()
        for op in blk.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        blk.vars = {n: v for n, v in blk.vars.items() if n in used}
        p._parameters = {n: v for n, v in p._parameters.items()
                         if n in blk.vars}
        return p

    def fingerprint(self) -> str:
        """Process-STABLE content hash of the program structure (op
        descs/attrs + var shapes/dtypes/persistability via to_dict) —
        the disk compile-cache key component (core/compile_cache.py).
        Reference counterpart: the serialized ProgramDesc proto bytes
        (reference framework/program_desc.h:38 Proto(); python
        framework.py:2932 Program.desc serialization) that identify
        the reference's `__model__` artifact on disk.

        Deliberately NOT the process-local `_uid` (a fresh process
        re-building the identical program gets a new _uid but must hit
        the on-disk executable). Op `_uid`s ARE included: they are
        position-derived (identical builds agree) and they salt
        sampling-op noise, so two programs differing only in op uids
        compile to different executables. Cached per `_version`
        (Pass.apply bumps it, invalidating the cached digest the same
        way it invalidates in-memory executables)."""
        cached = getattr(self, "_fingerprint_cache", None)
        if cached is not None and cached[0] == self._version:
            return cached[1]
        from .compile_cache import canonical_digest

        digest = canonical_digest(self.to_dict())
        self._fingerprint_cache = (self._version, digest)
        return digest

    # --- serialization -----------------------------------------------------
    def _to_analysis_dict(self):
        """Minimal structural dict for the native dataflow analyzer:
        op types + io names + var persistability only — skips attribute
        payloads (ndarrays etc.) that analysis never reads."""
        blocks = []
        for blk in self.blocks:
            blocks.append({
                "idx": blk.idx,
                "parent_idx": blk.parent_idx,
                "vars": [{"name": v.name, "persistable": v.persistable}
                         for v in blk.vars.values()],
                "ops": [{"type": op.type, "inputs": op.inputs,
                         "outputs": op.outputs}
                        for op in blk.ops],
            })
        return {"blocks": blocks, "parameters": list(self._parameters)}

    def to_dict(self):
        return {"blocks": [b.to_dict() for b in self.blocks],
                "parameters": list(self._parameters),
                "version": 1}

    @staticmethod
    def from_dict(d) -> "Program":
        p = Program()
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            p.blocks.append(blk)
        for bd, blk in zip(d["blocks"], p.blocks):
            for vd in bd["vars"]:
                blk.vars[vd["name"]] = Variable.from_dict(blk, vd)
            for od in bd["ops"]:
                blk.ops.append(Operator.from_dict(blk, od, p))
        for name in d.get("parameters", []):
            if name in p.global_block.vars:
                p._parameters[name] = p.global_block.vars[name]
        return p

    def __repr__(self):
        nops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={nops})"


# --- default program registry (reference framework.py:3390-3458) ----------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, p
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)
