"""Warm-start layer: persistent, content-addressed compile cache.

Reference counterpart: the reference amortizes per-step setup with
Executor::Prepare / RunPreparedContext (reference
paddle/fluid/framework/executor.cc:337,377) and ships inference as a
pre-optimized ``__model__`` artifact — a fresh serving process never
re-runs the analysis passes. paddle_tpu's analogue of "setup" is the
XLA compile itself, and until now every process start re-traced and
re-compiled every executable. PERF.md's serving table shows that cost
landing inside the traffic window collapses the batching win from
9.7x to 1.04x; ``aot_warmup()`` only MOVES those compiles ahead of
traffic, it does not eliminate them.

This module eliminates them across processes:

* Keys are content-addressed: ``Program.fingerprint()`` (canonical
  structural hash, NOT the process-local ``_uid``) + feed specs +
  fetch names + AMP token + parallel-scope token + backend + device
  count + jax/jaxlib version strings. Any component changing (a
  Pass.apply version bump, a jaxlib upgrade, an AMP toggle) is a
  clean miss, never a stale executable.
* Values are serialized AOT executables via
  ``jax.experimental.serialize_executable`` (API feature-detected the
  way native/hlo_exec.py detects the StableHLO bridges), plus the aux
  metadata (state_in/const_in/state_out names, feed/fetch lists,
  write-only carry specs) needed to rehydrate a compiled step with
  ZERO tracing. When executable serialization is unavailable the
  entry persists lowered StableHLO instead — tracing is still
  skipped; only the backend compile is redone at load.
* Corrupt or stale entries are discarded with a named reason
  (``CompileCache.discards``) and the caller recompiles — a broken
  cache can slow a process down, never break it.

Gated by ``FLAGS_compile_cache={off,ro,rw}`` +
``FLAGS_compile_cache_dir``; wired through every Executor compile
path (run / run_steps / the InferenceServer aot_warmup bucket ladder)
in core/executor.py. ``FLAGS_compile_cache_max_entries`` /
``_max_bytes`` bound the on-disk size with LRU-by-mtime pruning on
write (loads refresh mtime), counted in ``prune_count`` — multi-model
churn (inference/runtime hot swap) otherwise grows the root without
bound.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import pickle
import tempfile
import time
import warnings
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = ["CompileCache", "active_cache", "canonical_digest",
           "version_token"]

# bump when the entry layout changes: old-format entries become clean
# named-reason discards instead of unpickling hazards
_MAGIC = "ptp-exe-cache-v1"

# tests force the StableHLO persistence path without uninstalling the
# serialize_executable API
_FORCE_STABLEHLO = [False]


def _canon(o):
    """json.dumps default= hook: canonicalize numpy/enum/odd values so
    digests are process-stable."""
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return {"__ndarray__": o.reshape(-1).tolist(),
                "dtype": str(o.dtype), "shape": list(o.shape)}
    if isinstance(o, (set, frozenset)):
        return sorted(map(repr, o))
    value = getattr(o, "value", None)
    if value is not None and isinstance(value, (str, int)):
        return value
    return repr(o)


def canonical_digest(parts: Dict[str, Any]) -> str:
    """Stable sha256 of a JSON-canonicalized structure — the key/
    fingerprint hasher (reference analogue: the serialized
    ProgramDesc bytes that identify a `__model__` artifact, reference
    python/paddle/fluid/io.py:865 save_inference_model writes
    program.desc.serialize_to_string())."""
    blob = json.dumps(parts, sort_keys=True, default=_canon).encode()
    return hashlib.sha256(blob).hexdigest()


# computed once per process: hashing ~170 .py files (~2.5 MB) costs
# milliseconds and only runs when the cache is actually consulted
_SOURCE_TOKEN: list = []


def _source_token() -> str:
    """Content hash of the paddle_tpu package's own .py sources. The
    program fingerprint hashes op DESCS, not op KERNELS — an epsilon
    fix inside ops/ changes the compiled math without changing any
    desc, and must be a clean cache miss, not a silently-stale
    executable with the old numerics. Content-based (not mtime) so
    identical code deployed into fresh containers still warm-starts."""
    if _SOURCE_TOKEN:
        return _SOURCE_TOKEN[0]
    h = hashlib.sha256()
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    try:
        paths = []
        for dirpath, dirnames, files in os.walk(pkg_root):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"]
            paths.extend(os.path.join(dirpath, f) for f in files
                         if f.endswith(".py"))
        for p in sorted(paths):
            h.update(p[len(pkg_root):].encode())
            with open(p, "rb") as f:
                h.update(f.read())
        token = h.hexdigest()
    except Exception:
        token = "unhashable-source"
    _SOURCE_TOKEN.append(token)
    return token


def version_token() -> Dict[str, str]:
    """Toolchain + framework version strings for the cache key
    (reference analogue: the version field baked into the serialized
    ProgramDesc, reference framework/framework.proto:188 `version`,
    checked at load): a serialized executable is an internal jaxlib
    artifact AND embeds this framework's kernel lowerings, so a bump
    of either must be a clean miss (tests spoof this to prove
    invalidation)."""
    import jax

    try:
        import jaxlib

        jl = getattr(getattr(jaxlib, "version", None), "__version__",
                     None) or getattr(jaxlib, "__version__", "unknown")
    except Exception:
        jl = "unknown"
    return {"jax": jax.__version__, "jaxlib": str(jl),
            "paddle_tpu_src": _source_token()}


def _serialize_fns():
    """Feature-detect the executable (de)serialization API — jaxlib
    renames bite (CLAUDE.md r6: three spellings drifted in this
    container alone), so never assume, always probe."""
    if _FORCE_STABLEHLO[0]:
        return None, None
    try:
        from jax.experimental import serialize_executable as se
    except Exception:
        return None, None
    return (getattr(se, "serialize", None),
            getattr(se, "deserialize_and_load", None))


class _StableHLOCallable:
    """Fallback rehydration: StableHLO text -> backend compile ->
    flatten/execute/unflatten wrapper matching the traced step fn's
    calling convention. Donation annotations survive in the module's
    input_output_alias, so donated state buffers behave exactly like
    the jit path (the executor re-gathers state from the scope each
    step)."""

    def __init__(self, loaded, in_tree, out_tree, in_dtypes):
        self._loaded = loaded
        self._in_tree = in_tree
        self._out_tree = out_tree
        self._in_dtypes = in_dtypes

    def __call__(self, *args):
        import jax
        import jax.numpy as jnp

        flat = jax.tree.flatten(args)[0]
        bufs = []
        for x, want in zip(flat, self._in_dtypes):
            if not isinstance(x, jax.Array) or str(x.dtype) != want:
                x = jnp.asarray(np.asarray(x).astype(want))
            bufs.append(x)
        outs = self._loaded.execute(bufs)
        return jax.tree.unflatten(self._out_tree, list(outs))


def _compile_stablehlo(text: str):
    """backend.compile with the hlo_exec.py API feature detection."""
    import jax
    from jax._src.lib import xla_client

    backend = jax.devices()[0].client
    opts = xla_client.CompileOptions()
    if hasattr(backend, "compile_and_load"):
        return backend.compile_and_load(text, backend.devices()[:1],
                                        opts)
    return backend.compile(text, opts)


class CompileCache:
    """One on-disk cache root (reference analogue: the pre-optimized
    `__model__` + params directory a serving process loads instead of
    re-running analysis, reference
    inference/api/analysis_predictor.cc:78 Init — here the persisted
    artifact is the compiled executable itself). Entries are pickle
    files named by the full key digest, sharded by a 2-char prefix;
    writes are atomic (tempfile + os.replace) so concurrent processes
    can share a root."""

    _obs_seq = itertools.count(1)

    def __init__(self, root: str, mode: str):
        assert mode in ("ro", "rw"), mode
        self.root = root
        self.mode = mode
        self.hit_count = 0        # entries successfully rehydrated
        self.miss_count = 0       # no entry on disk
        self.store_count = 0      # entries written this process
        self.prune_count = 0      # entries GC'd by the size bounds
        self.discards = []        # (digest, named reason)
        # observability: counters pulled at metrics.expose() time
        # (weakref provider; instances are process-global via _CACHES,
        # one per (root, mode) — the store label keeps co-resident
        # roots from emitting duplicate series, which a scraper
        # rejects wholesale)
        from ..observability import metrics as _obs_metrics

        self._obs_id = f"disk-cache-{next(CompileCache._obs_seq)}"
        _obs_metrics.register_provider(self)

    def _metrics_samples(self):
        lab = {"mode": self.mode, "store": self._obs_id}
        s = self.stats()
        return [(f"paddle_tpu_disk_cache_{k}_total", lab, v)
                for k, v in s.items()]

    @property
    def writable(self) -> bool:
        return self.mode == "rw"

    @property
    def last_discard_reason(self) -> Optional[str]:
        return self.discards[-1][1] if self.discards else None

    def _path(self, digest: str) -> str:
        return os.path.join(self.root, digest[:2], digest + ".ptexe")

    def _discard(self, digest: str, reason: str):
        """Named-reason discard (never a crash): drop the entry from
        disk when writable so the next process recompiles cleanly."""
        self.discards.append((digest, reason))
        warnings.warn(
            f"compile_cache: discarding entry {digest[:12]}...: "
            f"{reason} (recompiling)")
        if self.writable:
            try:
                os.unlink(self._path(digest))
            except OSError:
                pass

    # --- load ---------------------------------------------------------
    def load_executable(self, digest: str):
        """Rehydrate one entry -> (callable fn, meta dict) or None.
        fn has the traced step's calling convention. Corrupt /
        undeserializable entries are discarded with a named reason."""
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
        except FileNotFoundError:
            self.miss_count += 1
            return None
        except Exception as e:
            self._discard(digest, f"unreadable/corrupt entry "
                          f"({type(e).__name__}: {e})")
            return None
        if not isinstance(entry, dict) or entry.get("magic") != _MAGIC:
            self._discard(digest, "entry format mismatch (truncated "
                          "or written by an incompatible version)")
            return None
        mesh = (entry.get("meta") or {}).get("mesh")
        if mesh:
            # a sharded executable embeds its device assignment:
            # validate BEFORE deserializing so a process without the
            # mesh (fewer virtual devices, missing device ids) gets a
            # NAMED discard instead of a deserialization crash deep
            # inside jaxlib
            import jax

            have = {int(d.id) for d in jax.devices()}
            want = [int(i) for i in mesh.get("device_ids", [])]
            missing = [i for i in want if i not in have]
            if int(mesh.get("ndev", 0)) > len(have) or missing:
                self._discard(
                    digest,
                    f"mesh mismatch: entry compiled for a "
                    f"{mesh.get('ndev')}-device mesh "
                    f"(axes {mesh.get('axes')}, device ids {want}); "
                    f"this process has {len(have)} device(s) "
                    f"{sorted(have)[:8]} — recompiling for the local "
                    f"mesh")
                return None
        try:
            fmt = entry["format"]
            if fmt == "aot":
                _, deserialize = _serialize_fns()
                if deserialize is None:
                    raise RuntimeError(
                        "serialize_executable API unavailable in this "
                        "jax")
                fn = deserialize(entry["payload"], entry["in_tree"],
                                 entry["out_tree"])
            elif fmt == "stablehlo":
                loaded = _compile_stablehlo(entry["payload"])
                fn = _StableHLOCallable(loaded, entry["in_tree"],
                                        entry["out_tree"],
                                        entry["in_dtypes"])
            else:
                raise RuntimeError(f"unknown entry format {fmt!r}")
        except Exception as e:
            self._discard(digest, f"executable failed to rehydrate "
                          f"({type(e).__name__}: {e})")
            return None
        self.hit_count += 1
        # LRU signal for the size-bounded GC: a load refreshes the
        # entry's mtime so _prune drops cold entries, not the ones
        # serving processes still warm-start from. Deliberately NOT
        # gated on self.writable — the common fleet split is ro
        # serving processes + one rw writer doing the pruning, and an
        # ro reader that never touched mtime would look cold to the
        # writer's GC and get its hot entries evicted. mtime is cache
        # METADATA, not content; ro still never writes entries. A
        # permission failure (true read-only mount) is fine to
        # swallow: GC then degrades to FIFO for those readers.
        try:
            os.utime(path)
        except OSError:
            pass
        return fn, entry["meta"]

    # --- store --------------------------------------------------------
    def store_executable(self, digest: str, compiled, lowered,
                         out_shape, meta: Dict[str, Any]) -> bool:
        """Persist one AOT-compiled executable. `compiled` is the
        jax.stages.Compiled, `lowered` its Lowered (the StableHLO
        fallback source), `out_shape` the eval_shape output pytree
        (out_tree source when serialize() is unavailable). Failures
        are recorded, never raised — an unserializable program (e.g.
        one bridging the host via io_callback) simply stays
        process-local."""
        if not self.writable:
            return False
        import jax

        entry = {"magic": _MAGIC, "meta": meta,
                 "versions": version_token()}
        serialize, _ = _serialize_fns()
        try:
            if serialize is None:
                raise RuntimeError(
                    "serialize_executable API unavailable")
            payload, in_tree, out_tree = serialize(compiled)
            entry.update(format="aot", payload=payload,
                         in_tree=in_tree, out_tree=out_tree)
        except Exception as aot_err:
            if meta.get("mesh"):
                # the StableHLO fallback recompiles single-device at
                # load (`_compile_stablehlo`): a sharded module would
                # silently lose its mesh — stay process-local instead
                self.discards.append(
                    (digest, f"sharded executable not serializable "
                     f"(aot: {aot_err}); the StableHLO fallback is "
                     f"single-device — entry stays process-local"))
                return False
            try:
                in_avals = meta["in_avals"]
                flat, in_tree = jax.tree.flatten(in_avals)
                entry.update(
                    format="stablehlo",
                    payload=lowered.as_text(),
                    in_tree=in_tree,
                    out_tree=jax.tree.structure(out_shape),
                    in_dtypes=[str(a.dtype) for a in flat])
            except Exception as e:
                self.discards.append(
                    (digest, f"entry not serializable (aot: "
                     f"{aot_err}; stablehlo: {type(e).__name__}: "
                     f"{e})"))
                return False
        # in_avals are only needed at store time (tree/dtype
        # extraction above); keep entries lean
        entry["meta"] = {k: v for k, v in meta.items()
                         if k != "in_avals"}
        path = self._path(digest)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(entry, f)
                os.replace(tmp, path)  # atomic: readers never see a
                # half-written entry
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:
            self.discards.append(
                (digest, f"entry not writable ({type(e).__name__}: "
                 f"{e})"))
            return False
        self.store_count += 1
        self._prune()
        return True

    # --- size-bounded GC ---------------------------------------------
    def _entries(self, sweep_tmps: bool = False):
        """[(path, mtime, size)] of every entry on disk (cheap: a few
        hundred stat calls at most for any sane bound).
        ``sweep_tmps`` unlinks stale ``.tmp`` debris during the SAME
        walk so the per-store GC pays one directory pass, not two."""
        now = time.time()
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for f in files:
                p = os.path.join(dirpath, f)
                if f.endswith(".tmp"):
                    if not sweep_tmps:
                        continue
                    try:
                        if now - os.stat(p).st_mtime > \
                                self._TMP_STALE_S:
                            os.unlink(p)
                    except OSError:
                        pass
                    continue
                if not f.endswith(".ptexe"):
                    continue
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                out.append((p, st.st_mtime, st.st_size))
        return out

    def disk_usage(self) -> dict:
        entries = self._entries()
        return {"entries": len(entries),
                "bytes": int(sum(s for _, _, s in entries))}

    # a writer killed between mkstemp and os.replace leaves a
    # digest-sized .tmp that _entries() never counts; live writers
    # finish in well under a minute, so anything older is debris
    _TMP_STALE_S = 300.0

    def _prune(self):
        """LRU-by-mtime GC down to FLAGS_compile_cache_max_entries /
        _max_bytes (<= 0 = unbounded). Runs after each store; loads
        refresh mtime, so what goes is what no process warm-started
        from recently. Unlink races with concurrent writers are
        benign (missing file = already pruned)."""
        from ..flags import FLAGS

        max_entries = int(FLAGS.compile_cache_max_entries)
        max_bytes = int(FLAGS.compile_cache_max_bytes)
        if max_entries <= 0 and max_bytes <= 0:
            return  # GC off: stores stay O(1), no directory walks
        entries = self._entries(sweep_tmps=True)
        total = sum(s for _, _, s in entries)
        over_n = (len(entries) - max_entries) if max_entries > 0 else 0
        if over_n <= 0 and (max_bytes <= 0 or total <= max_bytes):
            return
        entries.sort(key=lambda e: e[1])  # oldest mtime first
        for path, _mtime, size in entries:
            if over_n <= 0 and (max_bytes <= 0 or total <= max_bytes):
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            self.prune_count += 1
            over_n -= 1
            total -= size

    def stats(self) -> dict:
        return {"hits": self.hit_count, "misses": self.miss_count,
                "stores": self.store_count,
                "prunes": self.prune_count,
                "discards": len(self.discards)}


# one CompileCache per (root, mode) per process so counters aggregate
# across executors (serving clones share it the way they share the
# in-memory cache)
_CACHES: Dict[Tuple[str, str], CompileCache] = {}


def active_cache() -> Optional[CompileCache]:
    """The process's CompileCache per FLAGS, or None when off
    (reference analogue: the gflags bridge gating optional engines,
    reference python/paddle/fluid/__init__.py:129 env-flag
    allowlist)."""
    from ..flags import FLAGS

    mode = FLAGS.compile_cache
    if mode == "off":
        return None
    root = os.path.abspath(FLAGS.compile_cache_dir)
    key = (root, mode)
    cache = _CACHES.get(key)
    if cache is None:
        cache = _CACHES[key] = CompileCache(root, mode)
    return cache
