"""Scope: hierarchical name -> runtime value map.

TPU-native analogue of the reference's Scope/Variable
(reference: paddle/fluid/framework/scope.h:45, variable.h). Values are JAX
arrays living on device (or small host numpy); the Executor reads the
persistable subset as functional state, runs a compiled step with donated
buffers, and writes the updated state back -- preserving the reference's
Python-visible mutation model (params updated "in place" by optimizer ops)
on top of JAX's functional purity (SURVEY.md hard part (e)).
"""
from __future__ import annotations

import itertools
from typing import Dict, Optional

import numpy as np


class TensorValue:
    """fluid LoDTensor-handle parity: scope.find_var(x).get_tensor()."""

    def __init__(self, scope: "Scope", name: str):
        self._scope = scope
        self._name = name

    def value(self):
        return self._scope._get(self._name)

    def set(self, array, place=None):
        self._scope._set(self._name, np.asarray(array))

    def set_lod(self, lod):
        self._scope._lods[self._name] = lod

    def lod(self):
        return self._scope._lods.get(self._name, [])

    def shape(self):
        v = self.value()
        return list(v.shape) if v is not None else None

    def __array__(self, dtype=None):
        arr = np.asarray(self.value())
        return arr.astype(dtype) if dtype is not None else arr


class ScopeVar:
    def __init__(self, scope, name):
        self._scope = scope
        self.name = name

    def get_tensor(self) -> TensorValue:
        return TensorValue(self._scope, self.name)


_scope_uid_counter = itertools.count()


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self._lods: Dict[str, list] = {}
        self.parent = parent
        self._kids = []
        # process-unique identity for caches keyed on "which scope":
        # id() is unsound after GC + address reuse
        self._uid = next(_scope_uid_counter)

    # --- fluid-style interface --------------------------------------------
    def var(self, name) -> ScopeVar:
        if name not in self._vars:
            self._vars[name] = None
        return ScopeVar(self, name)

    def find_var(self, name) -> Optional[ScopeVar]:
        s = self
        while s is not None:
            if name in s._vars:
                return ScopeVar(s, name)
            s = s.parent
        return None

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def local_var_names(self):
        return list(self._vars)

    # --- raw access used by the executor ----------------------------------
    def _get(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def _set(self, name, value):
        s = self
        while s is not None:
            if name in s._vars:
                s._vars[name] = value
                return
            s = s.parent
        self._vars[name] = value

    def has(self, name):
        s = self
        while s is not None:
            if name in s._vars and s._vars[name] is not None:
                return True
            s = s.parent
        return False

    def erase(self, name):
        self._vars.pop(name, None)
        self._lods.pop(name, None)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _reset_global_scope():
    global _global_scope
    _global_scope = Scope()
    return _global_scope
