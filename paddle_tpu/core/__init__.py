from . import types, program, registry, scope, executor  # noqa: F401
