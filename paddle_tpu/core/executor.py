"""Executor: lowers a whole Block to one XLA computation and runs it.

TPU-native replacement for the reference's interpret-loop Executor
(reference: paddle/fluid/framework/executor.cc:118,337,377 -- which runs
ops one-by-one on the host). Here Executor.run traces every op kernel in
the block through JAX and compiles the *entire* block into a single XLA
program (trace -> compile -> execute), so:

* the per-op host dispatch hot loop disappears;
* XLA fuses elementwise chains into matmul/conv epilogues (the reference
  needs explicit fuse passes, ir/fuse_*_pass.cc, for this);
* eager tensor GC (reference framework/garbage_collector.h) is subsumed by
  XLA buffer liveness analysis inside the compiled program;
* optimizer "in-place" param mutation is expressed as functional state
  threading with donated input buffers (true in-place update on TPU HBM).

Compiled programs are cached per (program version, feed/state shapes,
fetch set) -- the analogue of the reference's ExecutorPrepareContext
caching (executor.py:451 _run cache).
"""
from __future__ import annotations

import collections
import itertools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .program import Program, Variable, default_main_program
from .registry import get_op_info, is_registered, run_op, EMPTY_VAR
from .scope import Scope, global_scope
from .types import to_np_dtype

# feed/fetch are plumbing; `go` (reference operators/csp/go_op.cc) is
# a host-side detached-thread launcher that cannot live inside the
# traced XLA program — Executor.run fires it separately
_SKIP_OP_TYPES = ("feed", "fetch", "go")

RNG_VAR = "@RNG@"

_global_seed = [0]

# (program uid, version, native_build) -> (reason-or-None,), the
# Executor.prepare_unsupported_reason memo (wrapped in a tuple so a
# cached None is distinguishable from a miss)
_PREPARE_REASON_CACHE: Dict = {}


def seed(s: int):
    """Set the global PRNG seed (analogue of fluid Program.random_seed)."""
    _global_seed[0] = int(s)
    sc = global_scope()
    sc._vars.pop(RNG_VAR, None)


class TPUPlace:
    """Device placement tag (reference platform/place.h CUDAPlace/CPUPlace).

    On TPU the XLA client owns placement; this keeps the API surface and
    selects a jax device."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def device(self):
        devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


class CPUPlace(TPUPlace):
    def device(self):
        return jax.devices("cpu")[0] if any(
            d.platform == "cpu" for d in jax.devices()) else jax.devices()[0]

    def __repr__(self):
        return f"CPUPlace()"


class CUDAPlace(TPUPlace):
    """Compatibility alias -- maps onto the accelerator device."""

    def __repr__(self):
        return f"CUDAPlace({self.device_id})"


class CUDAPinnedPlace(CPUPlace):
    """reference platform/place.h:52 CUDAPinnedPlace (page-locked host
    staging). XLA owns host staging on TPU; behaves as a CPUPlace."""

    def __repr__(self):
        return "CUDAPinnedPlace()"


class _CompiledBlock:
    """One specialization of a block: jitted fn + binding metadata."""

    def __init__(self, fn, feed_names, state_in, const_in, state_out,
                 fetch_names):
        self.fn = fn
        self.feed_names = feed_names
        self.state_in = state_in      # mutated persistables (donated)
        self.const_in = const_in      # read-only persistables
        self.state_out = state_out    # names written back to scope
        self.fetch_names = fetch_names


class _CompiledScan(_CompiledBlock):
    """A K-step lax.scan specialization (Executor.run_steps): fn runs
    the whole K-step loop on device and returns stacked fetches."""

    def __init__(self, fn, feed_names, state_in, const_in, state_out,
                 fetch_names, write_only_specs, steps, stacked):
        super().__init__(fn, feed_names, state_in, const_in, state_out,
                         fetch_names)
        # state_out names never read by the block: they join the scan
        # carry (structure must be step-invariant) seeded with zeros
        # of these shapes; every iteration overwrites them
        self.write_only_specs = write_only_specs
        self.steps = steps
        self.stacked = stacked        # per-step xs vs one closed-over feed


class ExecutableCache:
    """Bounded in-memory executable cache (LRU).

    Reference counterpart: the ExecutorPrepareContext cache the
    Python Executor keeps per (program, scope) around
    Executor::Prepare (reference python/paddle/fluid/executor.py:451
    `Executor._get_program_cache`; reference
    framework/executor.cc:289 Prepare builds what is cached) — here
    the cached object is the compiled XLA executable, and the cache
    is bounded.

    The unbounded dict it replaces leaked one executable per program
    mutation: `Pass.apply` bumps `program._version`, so the old entry
    can never be hit again but was never dropped — a long-lived
    serving process accumulated stranded XLA executables forever.
    Capacity comes from `FLAGS_executor_cache_capacity` (<= 0 =
    unbounded); evictions are counted for observability. Shared
    across serving clones exactly like the dict was
    (AnalysisPredictor.clone passes the object through)."""

    _obs_seq = itertools.count(1)

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            from ..flags import FLAGS

            capacity = FLAGS.executor_cache_capacity
        self.capacity = int(capacity)
        self.evict_count = 0
        self.insert_count = 0
        self._d: "collections.OrderedDict" = collections.OrderedDict()
        # observability: residency/churn pulled at expose() time
        # (weakref provider — paddle_tpu/observability/metrics.py)
        self._obs_id = f"exe-cache-{next(ExecutableCache._obs_seq)}"
        from ..observability import metrics as _obs_metrics

        _obs_metrics.register_provider(self)
        # serving clones share one instance across batcher/caller
        # threads; the plain dict this replaces was GIL-atomic per op,
        # but get() here is a read + move_to_end pair racing
        # __setitem__'s eviction — lock the pairs
        import threading

        self._lock = threading.Lock()

    def get(self, key, default=None):
        with self._lock:
            try:
                value = self._d[key]
            except KeyError:
                return default
            self._d.move_to_end(key)
            return value

    def __getitem__(self, key):
        with self._lock:
            value = self._d[key]
            self._d.move_to_end(key)
            return value

    def __setitem__(self, key, value):
        with self._lock:
            if key not in self._d:
                self.insert_count += 1
            self._d[key] = value
            self._d.move_to_end(key)
            if self.capacity > 0:
                while len(self._d) > self.capacity:
                    self._d.popitem(last=False)
                    self.evict_count += 1

    def __contains__(self, key):
        with self._lock:
            return key in self._d

    def __len__(self):
        with self._lock:
            return len(self._d)

    def clear(self):
        with self._lock:
            self._d.clear()

    def stats(self) -> dict:
        """Cache-pressure snapshot for the runtime's capacity-planning
        surface (inference/runtime): residency, bound, and lifetime
        insert/evict counts — a rising evictions/inserts ratio means
        the bound is below the live working set and steady-state
        traffic is recompiling."""
        with self._lock:
            return {"size": len(self._d), "capacity": self.capacity,
                    "inserts": self.insert_count,
                    "evictions": self.evict_count}

    def _metrics_samples(self):
        """Pull-provider for observability.metrics.expose()."""
        lab = {"cache": self._obs_id}
        s = self.stats()
        return [
            ("paddle_tpu_executable_cache_size", lab, s["size"]),
            ("paddle_tpu_executable_cache_capacity", lab,
             s["capacity"]),
            ("paddle_tpu_executable_cache_inserts_total", lab,
             s["inserts"]),
            ("paddle_tpu_executable_cache_evictions_total", lab,
             s["evictions"]),
        ]


def _as_aval(x):
    """Example value -> the aval jit would see at call time (dtype
    canonicalized the way the dispatch path does, so AOT-lowered
    entry signatures match real calls)."""
    arr = x if isinstance(x, jax.Array) else np.asarray(x)
    return jax.ShapeDtypeStruct(
        tuple(arr.shape), jax.dtypes.canonicalize_dtype(arr.dtype))


def _dtype_from_str(s):
    """np.dtype(str) that also resolves ml_dtypes names (bfloat16 is
    not registered under np.dtype's string lookup)."""
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


_NATIVE_WARNED = [False]


def _native_usable(block):
    from .. import native

    if not native.available():
        return False
    return all(op.type in _SKIP_OP_TYPES or is_registered(op.type)
               for op in block.ops)


def _native_prog(block):
    from .. import native

    return native.NativeProgram.from_dict(
        block.program._to_analysis_dict())


def _warn_native_failure(what, exc):
    """A native-analysis failure degrades to the Python oracle — but
    never silently (VERDICT r2 weak #7): warn once per process, and
    under FLAGS_native_verify raise instead."""
    from ..flags import FLAGS

    if FLAGS.native_verify:
        raise RuntimeError(
            f"native {what} failed under FLAGS_native_verify: "
            f"{exc}") from exc
    if not _NATIVE_WARNED[0]:
        _NATIVE_WARNED[0] = True
        import warnings

        warnings.warn(
            f"native {what} failed ({type(exc).__name__}: {exc}); "
            f"falling back to the Python analyzer for this process. "
            f"Set FLAGS_native_verify=1 to raise instead.")


def _analyze_block(block, feed_names, fetch_names, nprog=None):
    """Classify vars: feed / state-in (from scope) / produced / fetched.

    Prefers the native C++ analyzer (paddle_tpu/native/src/analysis.cc,
    the reference's executor_gc_helper/reference_count_pass analogue);
    the Python path below is the fallback and the cross-check oracle
    (tests/test_native.py asserts both agree; FLAGS_native_verify=1
    cross-checks on every compile and raises on divergence).
    """
    from ..flags import FLAGS

    if _native_usable(block):
        try:
            nprog = nprog or _native_prog(block)
            mutated, const, state_out = nprog.analyze_block(
                block.idx, list(feed_names), list(fetch_names),
                list(_SKIP_OP_TYPES))
        except Exception as e:
            _warn_native_failure("block analysis", e)
        else:
            if FLAGS.native_verify:
                py = _analyze_block_py(block, feed_names, fetch_names)
                if (sorted(mutated), sorted(const),
                        sorted(state_out)) != tuple(
                            sorted(x) for x in py):
                    raise RuntimeError(
                        "native/Python block-analysis divergence: "
                        f"native={mutated, const, state_out} "
                        f"python={py}")
            return mutated, const, state_out
    return _analyze_block_py(block, feed_names, fetch_names)


def _last_use_plan(block, feed_names, fetch_names, nprog=None):
    """free_after[i]: vars whose LAST use is block op i — evicted from
    the trace env right after that op runs (the reference's
    executor_gc_helper eager-GC, computed natively in
    native/src/analysis.cc lastUsePlan and followed by the trace loop
    below; Python mirror is the oracle)."""
    from ..flags import FLAGS

    if _native_usable(block):
        try:
            nprog = nprog or _native_prog(block)
            plan = nprog.last_use_plan(
                block.idx, list(feed_names), list(fetch_names))
        except Exception as e:
            _warn_native_failure("last-use planning", e)
        else:
            if FLAGS.native_verify:
                py = _last_use_plan_py(block, feed_names, fetch_names)
                if [sorted(p) for p in plan] != \
                        [sorted(p) for p in py]:
                    raise RuntimeError(
                        "native/Python last-use plan divergence")
            return plan
    return _last_use_plan_py(block, feed_names, fetch_names)


def _last_use_plan_py(block, feed_names, fetch_names):
    protect = set(feed_names) | set(fetch_names)
    last_use = {}
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            last_use[n] = i
        for n in op.output_arg_names:
            last_use[n] = i
    plan = [[] for _ in block.ops]
    for name, i in last_use.items():
        if name == EMPTY_VAR or name in protect:
            continue
        var = block._find_var_recursive(name)
        if var is not None and var.persistable:
            continue
        plan[i].append(name)
    return [sorted(p) for p in plan]


def _analyze_block_py(block, feed_names, fetch_names):
    produced = set(feed_names)
    state_in = []
    written = []
    seen_in = set()
    for op in block.ops:
        if op.type in _SKIP_OP_TYPES:
            continue
        if not is_registered(op.type):
            raise RuntimeError(f"op {op.type!r} has no registered kernel")
        for name in op.input_arg_names:
            if name == EMPTY_VAR or name in produced or name in seen_in:
                continue
            seen_in.add(name)
            state_in.append(name)
        # sub-block reads resolve at trace time through the env too;
        # control-flow kernels declare their reads as op inputs.
        for name in op.output_arg_names:
            if name not in produced:
                produced.add(name)
                written.append(name)
    # persistable outputs must be written back to the scope
    state_out = []
    for name in written:
        var = block._find_var_recursive(name)
        if var is not None and var.persistable:
            state_out.append(name)
    for name in fetch_names:
        if name not in produced and name not in seen_in \
                and name not in feed_names:
            # fetching an untouched persistable straight from scope
            state_in.append(name)
            seen_in.add(name)
    # split state_in into mutated (donate) vs const
    mutated = [n for n in state_in if n in set(state_out)]
    const = [n for n in state_in if n not in set(state_out)]
    return mutated, const, state_out


def _build_step_fn(block, feed_names, mutated, const, state_out,
                   fetch_names, free_after=None):
    # pre-compile gate (reference op_desc.cc/operator.cc validate
    # before Run): FLAGS_static_check={off,warn,strict} runs the
    # analysis checker suite over the program ONCE per version —
    # strict raises EnforceNotMet with the PTA diagnostics instead of
    # letting a malformed program fail deep inside the jax trace
    from ..analysis import maybe_check_program

    maybe_check_program(block.program)
    keep = set(state_out) | set(fetch_names)

    def step(mut_state, const_state, feeds, rng):
        env = {}
        env.update(const_state)
        env.update(mut_state)
        env.update(feeds)
        rng_cell = [rng]
        for i, op in enumerate(block.ops):
            if op.type in _SKIP_OP_TYPES:
                continue
            run_op(op, env, rng_cell=rng_cell, rng_salt=op._uid)
            if free_after is not None:
                # native GC plan: drop tracers whose last use was this
                # op, bounding the trace env the way the reference's
                # eager GC bounds scope tensors (keep is belt-and-
                # braces: plans already protect state/fetches)
                for n in free_after[i]:
                    if n not in keep:
                        env.pop(n, None)
        new_state = {n: env[n] for n in state_out if n in env}
        fetches = [env[n] for n in fetch_names]
        # ops derive keys functionally (fold_in(step_key, uid)); the
        # step key itself advances exactly once per step here
        return new_state, fetches, jax.random.split(rng, 1)[0]

    return step


@jax.jit
def _finite_flags(vs):
    import jax.numpy as jnp

    return [jnp.all(jnp.isfinite(v)) for v in vs]


def _check_nan_inf(new_state, fetches, fetch_names):
    """FLAGS_check_nan_inf guard (reference framework/operator.cc:975
    checks each op's outputs after Run). The whole block is ONE XLA
    program here, so the per-op hook point does not exist; instead every
    mutated state buffer and fetched value is reduced to a single
    all-finite bit in one fused jit -- one scalar per variable crosses
    the host boundary, and the first offending variable is named."""
    import jax.numpy as jnp

    named = [(n, v) for n, v in new_state.items()
             if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)]
    named += [(f"fetch:{fetch_names[i]}", v)
              for i, v in enumerate(fetches)
              if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)]
    if not named:
        return
    flags = _finite_flags([v for _, v in named])
    for (name, _), ok in zip(named, flags):
        if not bool(ok):
            raise RuntimeError(
                f"Operator output contains NaN/Inf: variable {name!r} "
                f"(FLAGS_check_nan_inf is enabled)")


def _default_layout_specs(step, scope, mutated, const, feed_arrays,
                          place):
    """Pin the executor's jit boundary so state layouts stay stable.

    Left to itself, jax compiles each block's entry layouts to match the
    FIRST call's argument layouts, while XLA freely picks different
    layouts for the results. Mutated state then comes back in a layout
    the executable was not compiled for, and EVERY subsequent call
    re-lays-out those buffers outside the program -- on a tunneled TPU
    that is a host round-trip per buffer per step, which buried
    ResNet-50 (266 state vars) under ~25x pure relayout traffic.

    Fix: pin entry layouts to the layouts the scope arrays have NOW
    (what the first call would have used anyway), and pin each cycled
    state OUTPUT to its own input's layout, so state arrays flow
    through repeated steps byte-identical in layout and donation
    aliases cleanly. Everything else (fetches, fresh persistables, rng)
    stays compiler-chosen via an unconstrained Format() -- never force
    row-major: XLA tiles the two minor dims, so row-major [O,I,3,3]
    conv weights would pad ~100x in HBM.

    Returns (in_shardings, out_shardings), or None to fall back to
    plain jit (state not yet materialized, non-addressable arrays...).
    """
    mut_ex = {n: scope._get(n) for n in mutated}
    const_ex = {n: scope._get(n) for n in const}
    if any(v is None for v in mut_ex.values()) or \
            any(v is None for v in const_ex.values()):
        return None  # run() raises the friendly init error
    rng_ex = scope._get(RNG_VAR)
    if rng_ex is None:
        rng_ex = jax.random.PRNGKey(0)
    return _pin_state_layout_formats(step, mut_ex, const_ex,
                                     feed_arrays, rng_ex, place)


def _pin_state_layout_formats(fn, state_ex, const_ex, feeds_ex, rng_ex,
                              place):
    """Core of _default_layout_specs, generic over the step shape:
    `fn(state, const, feeds, rng) -> (new_state, fetches, rng)`; used
    for both the single-step block and the K-step scan (whose state is
    the scan carry and whose fetches are stacked [K, ...])."""
    try:
        from jax.experimental.layout import Format, Layout
        from jax.sharding import SingleDeviceSharding
    except Exception:
        return None
    if jax.device_count() > 1:
        # Pinning SingleDeviceSharding formats breaks programs that
        # shard_map over a multi-device mesh (context_parallel etc.);
        # the relayout problem this solves only exists on the
        # 1-real-chip tunneled host anyway.
        return None
    try:
        dev = place.device()
    except Exception:
        return None

    def fmt_of(x):
        f = getattr(x, "format", None)
        if f is not None and f.layout is not None:
            return f  # jax array: keep the layout it already has
        nd = len(getattr(x, "shape", ()))
        return Format(Layout(tuple(range(nd))), SingleDeviceSharding(dev))

    args = (state_ex, const_ex, dict(feeds_ex or {}), rng_ex)
    try:
        out_shape = jax.eval_shape(fn, *args)
        in_fmts = jax.tree.map(fmt_of, args)
        new_state_shape, fetches_shape, rng_shape = out_shape
        out_fmts = (
            {n: (fmt_of(state_ex[n]) if n in state_ex else Format())
             for n in new_state_shape},
            [Format() for _ in fetches_shape],
            Format(),
        )
    except Exception:
        return None
    return in_fmts, out_fmts


def _mesh_token(mesh):
    """Stable identity for a jax Mesh in executable cache keys.
    id(mesh) is unsound: a GC'd mesh whose address is reused by a new,
    DIFFERENT mesh would serve a stale executable. Axis names + shape +
    flat device ids pin the things that change how ops lower."""
    try:
        dev_ids = tuple(int(d.id) for d in mesh.devices.flat)
    except Exception:
        dev_ids = ()
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape), dev_ids)


def _parallel_scope_token():
    """Part of the executable cache key: the context-parallel and
    expert-parallel activation scopes change how attention/switch_moe
    ops LOWER at trace time (shard_map vs single-device), so entering
    or leaving a scope must miss the cache the same way an AMP toggle
    does — otherwise a stale dense executable is silently served."""
    try:
        from ..parallel.ring_attention import active_context_parallel
        from ..parallel.moe import active_expert_parallel
    except Exception:
        return ()
    tok = []
    cp = active_context_parallel()
    if cp is not None:
        mesh, axis, impl = cp
        tok.append(("cp", _mesh_token(mesh), axis, impl))
    ep = active_expert_parallel()
    if ep is not None:
        mesh, axis = ep
        tok.append(("ep", _mesh_token(mesh), axis))
    return tuple(tok)


def _var_np_dtype(block, name, default=np.float32):
    v = block._find_var_recursive(name)
    if v is None or v.dtype is None:
        return default
    return to_np_dtype(v.dtype)


def _check_feed_shape(block, name, value):
    """Validate a feed against the declared var shape up front: a rank
    or fixed-dim mismatch would otherwise surface as a raw jax
    broadcast/reshape error deep inside the traced block (reference
    DataFeeder checks shapes the same way)."""
    var = block._find_var_recursive(name)
    if var is None or var.shape is None:
        return
    # extract the dense part the same way _coerce_feed will: (data,
    # lod) legacy tuples carry their array behind one indirection
    dense = value
    if isinstance(dense, tuple) and len(dense) == 2:
        dense = dense[0]
    got = getattr(dense, "shape", None)
    if got is None or callable(got):
        # LoDTensor's .shape is a METHOD; lists have none -- fall back
        # to materializing (the jax-array fast path above avoids a
        # device readback for the common case)
        try:
            got = np.asarray(dense).shape
        except Exception:
            return  # exotic feed: let _coerce_feed handle it
    got = tuple(got)
    want = tuple(var.shape)
    ok = len(got) == len(want) and all(
        w < 0 or g == w for g, w in zip(got, want))
    if not ok:
        raise ValueError(
            f"feed {name!r} has shape {got} but the "
            f"program declares {want} (-1 = any); check the "
            f"batch layout or the data() declaration")


def _first_host_effect_op(block) -> Optional[str]:
    """Name of the first host-bridging op (registry host_effect flag)
    in `block` or any sub-block, else None. Shared by the scan
    fallback (host ops cannot live in a device-resident lax.scan) and
    the disk compile cache gate (io_callback closures are
    process-local pointers — a serialized executable carrying one
    would crash or corrupt a fresh process)."""
    from .program import Block

    seen = set()

    def walk(blk):
        for op in blk.ops:
            if op.type in ("feed", "fetch"):
                continue
            if is_registered(op.type) and \
                    get_op_info(op.type).host_effect:
                return op.type
            for v in op.attrs.values():
                if isinstance(v, Block) and id(v) not in seen:
                    seen.add(id(v))
                    r = walk(v)
                    if r is not None:
                        return r
        return None

    return walk(block)


def _scan_fallback_reason(program):
    """Why a program cannot lower into the K-step scan executor
    (Executor.run_steps): returns None when scannable, else the named
    reason the per-step fallback runs instead. Host-bridging ops
    (io_callback readers, py_func, go threads, print/save/load, PS
    send/recv) have once-per-step host semantics that a device-resident
    lax.scan cannot honor; sub-blocks (while/conditional) are walked
    too so a host op inside a loop body is caught."""
    from .compiler import CompiledProgram

    if isinstance(program, CompiledProgram):
        return ("CompiledProgram (data-parallel / inference-compiled) "
                "programs run through their own per-step path")
    from ..flags import FLAGS

    if FLAGS.native_build:
        return ("FLAGS_native_build executes C++-built programs one "
                "step at a time")
    host_op = _first_host_effect_op(program.global_block)
    if host_op is not None:
        return (f"op {host_op!r} bridges to the host "
                f"(io_callback / host threads) and cannot be "
                f"lowered into a device-resident lax.scan "
                f"over steps")
    return None


def _record_compile_event(kind, program, tier, t0, fn=None):
    """Observability: one global 'compile' span per executable
    RESOLUTION that was not a memory hit — annotated with the
    program's content fingerprint, the cache tier that satisfied it
    (``disk`` = warm-start rehydration, ``cold`` = trace + XLA
    compile; a memory hit never lands here, which is what lets the
    serving tests assert zero steady-state compile spans), and
    ``compiled.memory_analysis()`` sizes when the executable exposes
    them (AOT-compiled paths; plain-jit callables skip the sizes).
    Gated on FLAGS_observability=trace; at lower levels this is one
    boolean check per compile (compiles are rare by design)."""
    from ..observability import tracing as obs_tracing

    if not obs_tracing.trace_on():
        return
    attrs = {"kind": kind, "tier": tier,
             "fingerprint": program.fingerprint()[:16]}
    ma = getattr(fn, "memory_analysis", None)
    if ma is not None:
        try:
            m = ma()
            for field in ("temp_size_in_bytes",
                          "argument_size_in_bytes",
                          "output_size_in_bytes",
                          "generated_code_size_in_bytes"):
                v = getattr(m, field, None)
                if v is not None:
                    attrs[field] = int(v)
        except Exception:
            pass  # backend without memory analysis: annotate less
    obs_tracing.record_global_event("compile", t0, time.monotonic(),
                                    **attrs)


def _cost_probe_avals(compiled, scope, feed_arrays, write_only=None):
    """Aval tuple matching the compiled fn's call signature — the
    lazy cost-analysis probe (observability/costmodel.py): shape
    structs only, never arrays, so stashing a probe pins no buffers
    (the PreparedProgram example-feed discipline). None when scope
    state is uninitialized (run() raises its friendly error before
    analysis could matter) or any value defies aval-ing."""
    try:
        mut = {n: scope._get(n) for n in compiled.state_in}
        const = {n: scope._get(n) for n in compiled.const_in}
        if any(v is None for v in mut.values()) \
                or any(v is None for v in const.values()):
            return None
        rng = scope._get(RNG_VAR)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        carry = {n: _as_aval(v) for n, v in mut.items()}
        for n, spec in (write_only or {}).items():
            carry[n] = jax.ShapeDtypeStruct(tuple(spec.shape),
                                            spec.dtype)
        return (carry,
                {n: _as_aval(v) for n, v in const.items()},
                {n: _as_aval(v)
                 for n, v in (feed_arrays or {}).items()},
                _as_aval(rng))
    except Exception:
        return None


def _note_cost_model(program, fn, kind, feed_specs, compiled=None,
                     scope=None, feed_arrays=None, write_only=None):
    """Compile-time hook feeding the executable cost model
    (observability/costmodel.py): direct analysis for AOT Compiled
    fns, an aval probe for live-jit ones. Rides the compile budget —
    never a request path."""
    from ..observability import costmodel as obs_costmodel

    avals = None
    if compiled is not None and scope is not None \
            and not hasattr(fn, "cost_analysis"):
        avals = _cost_probe_avals(compiled, scope, feed_arrays,
                                  write_only=write_only)
    obs_costmodel.note_executable(program, fn, kind,
                                  feed_specs=feed_specs, avals=avals)


class Executor:
    """fluid.Executor parity (reference python/paddle/fluid/executor.py:451).
    """

    _obs_seq = itertools.count(1)

    def __init__(self, place: Optional[TPUPlace] = None,
                 donate: bool = True, cache: Optional[Dict] = None):
        # donate=False for executors whose scope is shared across
        # threads (AsyncExecutor Hogwild workers): a donated buffer is
        # deleted after the step, which would break concurrent readers
        self.place = place or TPUPlace()
        self.donate = donate
        # `cache` lets serving workers SHARE one executable cache
        # (AnalysisPredictor.clone): the keys carry the process-unique
        # program _uid + _version, so sharing the dict across executors
        # running the same program object is sound — a warmed bucket
        # compiled by one worker is a cache hit for every other.
        self._cache = ExecutableCache() if cache is None else cache
        # observability: how many XLA specializations THIS executor
        # built vs served from cache (serving perf is unverifiable
        # without these — the bucket-bound tests read them)
        self.compile_count = 0
        self.cache_hit_count = 0
        # executables rehydrated from the on-disk warm-start cache
        # (core/compile_cache.py) WITHOUT tracing or compiling
        self.disk_load_count = 0
        # run_steps: named reason the last call used the per-step
        # fallback (None = the K-step scan path ran)
        self.last_run_steps_fallback: Optional[str] = None
        # observability: the counters above are pulled at expose()
        # time (weakref provider; see _metrics_samples)
        self._obs_id = f"executor-{next(Executor._obs_seq)}"
        from ..observability import metrics as _obs_metrics

        _obs_metrics.register_provider(self)

    def _metrics_samples(self):
        """Pull-provider for observability.metrics.expose(): the
        compile/hit/disk-load/evict counters serving stats already
        read, re-registered into the central registry."""
        lab = {"executor": self._obs_id}
        return [
            ("paddle_tpu_executor_compiles_total", lab,
             self.compile_count),
            ("paddle_tpu_executor_cache_hits_total", lab,
             self.cache_hit_count),
            ("paddle_tpu_executor_disk_loads_total", lab,
             self.disk_load_count),
            ("paddle_tpu_executor_cache_evictions_total", lab,
             self.cache_evict_count),
        ]

    @property
    def cache_evict_count(self) -> int:
        return getattr(self._cache, "evict_count", 0)

    def close(self):
        self._cache.clear()
        self._go_threads = []

    # ------------------------------------------------------------------
    def _launch_go_ops(self, block, scope, feed_arrays):
        """Fire each `go` op's sub-block on a detached thread against
        a SNAPSHOT env (reference go_op.cc RunImpl: child scope,
        inputs copied in, scope dropped when the thread ends). Thread
        handles are kept on the executor so tests can join; the
        reference detaches outright."""
        import threading

        self._go_threads = [
            t for t in getattr(self, "_go_threads", [])
            if t.is_alive()]
        for go_idx, op in enumerate(block.ops):
            if op.type != "go":
                continue
            # Producers visible to THIS go op: only ops BEFORE it in
            # block order. A whole-block first-writer map could
            # recompute a value the reference's eager executor never
            # observes at the go point (a var first written later, or
            # rewritten between writes); those cases are named errors.
            producer, multi_writer, late = {}, set(), {}
            for p in block.ops[:go_idx]:
                if p.type in _SKIP_OP_TYPES:
                    continue
                for n in p.output_arg_names:
                    if n in producer:
                        multi_writer.add(n)
                    else:
                        producer[n] = p
            for p in block.ops[go_idx + 1:]:
                if p.type in _SKIP_OP_TYPES:
                    continue
                for n in p.output_arg_names:
                    late.setdefault(n, p)
            sub = op.attrs["sub_block"]
            env = {}
            # a go input may be a main-block INTERMEDIATE: under the
            # traced executor those never materialize in the scope, so
            # the thread recomputes the (deterministic) producing
            # chain from scope/feed roots — observably the value the
            # reference's eager executor would have found in the scope
            prefix, stack, seen = [], list(op.inputs.get("X", [])), set()
            while stack:
                n = stack.pop()
                if n in seen:
                    continue
                seen.add(n)
                v = feed_arrays.get(n)
                if v is None:
                    v = scope._get(n)
                if v is not None:
                    # COPY, on device: the step jit donates state
                    # buffers (donate_argnums), so a bare reference
                    # would be a deleted buffer by the time the
                    # thread reads it. jnp.array(copy=True) stays a
                    # device-device copy — no host round-trip.
                    env[n] = jnp.array(v, copy=True)
                    continue
                p = producer.get(n)
                if p is None:
                    lp = late.get(n)
                    if lp is not None:
                        raise RuntimeError(
                            f"go: captured var {n!r} is first written "
                            f"by op {lp.type!r} AFTER the go op; the "
                            f"reference's eager executor would not "
                            f"observe it at the go point")
                    raise RuntimeError(
                        f"go: input var {n!r} is neither fed, in the "
                        f"scope, nor produced by the block")
                if n in multi_writer:
                    raise RuntimeError(
                        f"go: captured var {n!r} has multiple writers "
                        f"before the go op; recomputing it in the go "
                        f"thread is ambiguous. Route the value "
                        f"through a persistable var instead.")
                if p.type in ("py_func", "print"):
                    raise RuntimeError(
                        f"go: captured var {n!r} is produced by the "
                        f"host-effecting op {p.type!r}; recomputing "
                        f"it in the go thread would double its side "
                        f"effects. Route the value through a "
                        f"persistable var instead.")
                prefix.append(p)
                stack.extend(x for x in p.input_arg_names
                             if x != EMPTY_VAR)
            order = {id(o): i for i, o in enumerate(block.ops)}
            prefix = sorted({id(p): p for p in prefix}.values(),
                            key=lambda o: order[id(o)])
            salt = getattr(op, "_uid", 0)

            def worker(sub=sub, env=env, prefix=tuple(prefix),
                       salt=salt):
                try:
                    cell = [jax.random.PRNGKey(_global_seed[0] + salt)]
                    for o in prefix:
                        run_op(o, env, rng_cell=cell, rng_salt=o._uid)
                    for o in sub.ops:
                        run_op(o, env, rng_cell=cell, rng_salt=o._uid)
                    # env discarded: the reference destroys the child
                    # scope when the thread finishes
                except Exception as e:  # fire-and-forget, but LOUD
                    import warnings

                    warnings.warn(
                        f"go thread failed: {type(e).__name__}: {e}")

            t = threading.Thread(target=worker, daemon=True)
            t.start()
            self._go_threads.append(t)

    # ------------------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, feed_var_name="feed", fetch_var_name="fetch",
            scope: Optional[Scope] = None, return_numpy: bool = True,
            use_program_cache: bool = True):
        program = program or default_main_program()
        # CompiledProgram (data-parallel / inference-optimized) delegates
        from .compiler import CompiledProgram

        if isinstance(program, CompiledProgram):
            return program._run(self, feed, fetch_list, scope, return_numpy)
        scope = scope or global_scope()
        feed = dict(feed or {})
        fetch_names = _to_fetch_names(fetch_list)
        block = program.global_block
        for name in fetch_names:
            if not block.has_var(name) and name not in feed:
                raise KeyError(
                    f"fetch target {name!r} does not exist in the "
                    f"program")
        for name, value in feed.items():
            _check_feed_shape(block, name, value)

        try:
            device = self.place.device()
        except Exception:
            device = None
        # Pre-committing inputs to one device conflicts with programs that
        # shard_map over a multi-device mesh (context_parallel etc.) --
        # committed single-device args can't be auto-resharded. The upload
        # fast path only matters on the 1-real-chip bench host anyway.
        if device is not None and jax.device_count() > 1:
            device = None
        feed_arrays = {}
        feed_specs = []
        for name, val in feed.items():
            arr = _coerce_feed(val, _var_np_dtype(block, name))
            feed_specs.append((name, arr.shape, str(arr.dtype)))
            # Explicit transfer instead of passing numpy into the jitted
            # call: the PJRT argument-upload path can be far slower than
            # device_put for incompressible data (50x on a tunneled TPU).
            if device is not None and not isinstance(arr, jax.Array):
                arr = jax.device_put(arr, device)
            feed_arrays[name] = arr

        if any(op.type == "go" for op in block.ops):
            self._launch_go_ops(block, scope, feed_arrays)

        from .. import amp
        from ..flags import FLAGS

        if FLAGS.native_build:
            # the train-step XLA program is BUILT IN C++ (xla_train
            # kernel registry) and consumed in-process via StableHLO;
            # the traced path below stays the cross-check oracle
            if amp.enabled():
                raise RuntimeError(
                    "FLAGS_native_build does not compose with AMP "
                    "yet; the native kernel slice builds the block "
                    "at its declared dtypes")
            nkey = ("native", program._uid, program._version,
                    tuple(sorted(feed_specs)), tuple(fetch_names),
                    scope._uid)
            step = self._cache.get(nkey) if use_program_cache \
                else None
            if step is None:
                from ..native.hlo_exec import NativeBuiltStep

                step = NativeBuiltStep(program, scope, feed_arrays,
                                       fetch_names)
                self.compile_count += 1
                if use_program_cache:
                    self._cache[nkey] = step
            else:
                self.cache_hit_count += 1
            fetched = step.run(scope, feed_arrays)
            out = [fetched[n] for n in fetch_names]
            if FLAGS.check_nan_inf:
                _check_nan_inf(
                    {n: scope._get(n) for n in step.state_out_names},
                    out, fetch_names)
            if return_numpy:
                out = [np.asarray(v) for v in out]
            return out

        key = self._block_cache_key(program, feed_specs, fetch_names)
        compiled = self._cache.get(key) if use_program_cache else None
        if compiled is None:
            compiled = self._resolve_block(
                program, block, tuple(sorted(feed_specs)), fetch_names,
                scope, feed_arrays)
            if use_program_cache:
                self._cache[key] = compiled
        else:
            self.cache_hit_count += 1

        mut = self._scope_state(scope, compiled.state_in, device)
        const_st = self._scope_state(scope, compiled.const_in, device)
        rng = scope._get(RNG_VAR)
        if rng is None:
            prog_seed = getattr(program, "_seed", None)
            rng = jax.random.PRNGKey(
                prog_seed if prog_seed is not None else _global_seed[0])
        new_state, fetches, rng_out = compiled.fn(
            mut, const_st, feed_arrays, rng)
        if FLAGS.check_nan_inf:
            _check_nan_inf(new_state, fetches, fetch_names)
        scope._set(RNG_VAR, rng_out)
        for n, v in new_state.items():
            scope._set(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _scope_state(self, scope, names, device):
        """Gather scope values for `names`, device-committing host
        arrays once (the slow-upload avoidance of run(): device_put
        beats the PJRT argument-upload path for incompressible data)."""
        out = {}
        for n in names:
            v = scope._get(n)
            if v is None:
                raise RuntimeError(
                    f"Variable {n!r} is used before initialization -- "
                    f"run the startup program first")
            if device is not None and not isinstance(v, jax.Array):
                v = jax.device_put(np.asarray(v), device)
                scope._set(n, v)
            out[n] = v
        return out

    # ------------------------------------------------------------------
    def run_steps(self, program: Optional[Program] = None, feed=None,
                  fetch_list=None, steps: Optional[int] = None,
                  scope: Optional[Scope] = None,
                  return_numpy: bool = True,
                  use_program_cache: bool = True):
        """Run K training steps as ONE device-resident lax.scan.

        The reference keeps its hot loop in C++ exactly to keep the
        host out of the step path (reference framework/executor.cc
        RunPreparedContext loop; layers/io.py double_buffer H2D
        staging). The TPU-native equivalent is scanning the whole
        compiled step over K on device: K Python dispatches + K
        potential tunnel round-trips collapse into 1 dispatch + 1
        stacked readback (~75 ms per avoided readback on the tunneled
        chip -- PERF.md "Host dispatch & the multi-step scan").

        feed is either ONE dict (the same batch every step, the bench
        harness case -- it enters the scan as a closed-over constant)
        or a list of K dicts (K batches are stacked and staged on
        device up front, entering as per-step scan xs). Returns one
        stacked [K, ...] array per fetch.

        Step semantics match K sequential run() calls exactly: the
        step PRNG key advances once per scan iteration, so sampling
        ops (dropout...) draw the identical per-step noise, and the
        final persistable state written back to the scope is the
        K-th step's (loss trajectories agree to float tolerance --
        tests/test_run_steps.py pins 1e-6).

        Programs that cannot scan fall back to K sequential run()
        calls with the named reason recorded on
        `self.last_run_steps_fallback` (None when the scan path ran):
        host-bridging ops (io_callback readers, py_func, go, print/
        save/load, PS send/recv), CompiledProgram, FLAGS_native_build.
        The scan executable is cached under its own key (program
        _uid/_version, per-step feed specs, fetch set, K, AMP and
        parallel-scope tokens), so Pass.apply version bumps invalidate
        it the same way they invalidate run()'s cache.
        """
        program = program or default_main_program()
        scope = scope or global_scope()
        feeds_seq = None
        if isinstance(feed, (list, tuple)):
            feeds_seq = [dict(f) for f in feed]
            if not feeds_seq:
                raise ValueError("run_steps: empty feed list")
            if steps is None:
                steps = len(feeds_seq)
            if int(steps) != len(feeds_seq):
                raise ValueError(
                    f"run_steps: steps={steps} but {len(feeds_seq)} "
                    f"feed dicts were given")
            names0 = set(feeds_seq[0])
            if any(set(f) != names0 for f in feeds_seq):
                raise ValueError(
                    "run_steps: all per-step feed dicts must bind "
                    "the same variable names")
        else:
            feed = dict(feed or {})
            if steps is None:
                raise ValueError(
                    "run_steps: steps=K is required when feeding one "
                    "dict (pass a list of K dicts for per-step "
                    "batches)")
        steps = int(steps)
        if steps < 1:
            raise ValueError(
                f"run_steps: steps must be >= 1, got {steps}")

        reason = _scan_fallback_reason(program)
        self.last_run_steps_fallback = reason
        if reason is not None:
            self._warn_scan_fallback(program, reason)
            return self._run_steps_fallback(
                program, feed, feeds_seq, fetch_list, steps, scope,
                return_numpy, use_program_cache)

        fetch_names = _to_fetch_names(fetch_list)
        block = program.global_block
        first_feed = feeds_seq[0] if feeds_seq is not None else feed
        for name in fetch_names:
            if not block.has_var(name) and name not in first_feed:
                raise KeyError(
                    f"fetch target {name!r} does not exist in the "
                    f"program")
        try:
            device = self.place.device()
        except Exception:
            device = None
        if device is not None and jax.device_count() > 1:
            # same multi-device caveat as run(): committed single-
            # device args can't be auto-resharded by shard_map programs
            device = None

        feed_arrays = {}
        feed_specs = []  # PER-STEP specs (what each scan body sees)
        if feeds_seq is not None:
            for name in sorted(feeds_seq[0]):
                dt = _var_np_dtype(block, name)
                cols = [_coerce_feed(f[name], dt) for f in feeds_seq]
                _check_feed_shape(block, name, cols[0])
                if all(isinstance(c, jax.Array) for c in cols):
                    arr = jnp.stack(cols)  # already device-resident
                else:
                    arr = np.stack([np.asarray(c) for c in cols])
                    if device is not None:
                        # ONE staging transfer for all K batches
                        arr = jax.device_put(arr, device)
                feed_arrays[name] = arr
                feed_specs.append(
                    (name, tuple(arr.shape[1:]), str(arr.dtype)))
        else:
            for name, val in feed.items():
                _check_feed_shape(block, name, val)
                arr = _coerce_feed(val, _var_np_dtype(block, name))
                feed_specs.append(
                    (name, tuple(arr.shape), str(arr.dtype)))
                if device is not None and not isinstance(arr, jax.Array):
                    arr = jax.device_put(arr, device)
                feed_arrays[name] = arr

        from .. import amp
        from ..flags import FLAGS

        key = self._scan_cache_key(program, feed_specs, fetch_names,
                                   steps, feeds_seq is not None)
        compiled = self._cache.get(key) if use_program_cache else None
        if compiled is None:
            compiled = self._resolve_scan(
                program, block, tuple(sorted(feed_specs)), fetch_names,
                scope, steps, feeds_seq is not None, feed_arrays,
                device)
            if use_program_cache:
                self._cache[key] = compiled
        else:
            self.cache_hit_count += 1

        carry = self._scope_state(scope, compiled.state_in, device)
        const_st = self._scope_state(scope, compiled.const_in, device)
        for n, spec in compiled.write_only_specs.items():
            # zeros placeholder: step 1 overwrites it; the carry just
            # needs a step-invariant structure
            carry[n] = jnp.zeros(spec.shape, spec.dtype)
        rng = scope._get(RNG_VAR)
        if rng is None:
            prog_seed = getattr(program, "_seed", None)
            rng = jax.random.PRNGKey(
                prog_seed if prog_seed is not None else _global_seed[0])
        fin_state, ys, rng_out = compiled.fn(
            carry, const_st, feed_arrays, rng)
        if FLAGS.check_nan_inf:
            _check_nan_inf(fin_state, ys, fetch_names)
        scope._set(RNG_VAR, rng_out)
        for n, v in fin_state.items():
            scope._set(n, v)
        if return_numpy:
            return [np.asarray(v) for v in ys]
        return list(ys)

    def _warn_scan_fallback(self, program, reason):
        """Named-reason visibility: fallbacks are correct but slower;
        warn once per (program, reason) so a bench silently losing the
        scan win is noticed."""
        warned = getattr(self, "_scan_fallback_warned", None)
        if warned is None:
            warned = self._scan_fallback_warned = set()
        tok = (program._uid if isinstance(program, Program)
               else id(program), reason)
        if tok in warned:
            return
        warned.add(tok)
        import warnings

        warnings.warn(
            f"run_steps: falling back to the per-step path: {reason}")

    def _run_steps_fallback(self, program, feed, feeds_seq, fetch_list,
                            steps, scope, return_numpy,
                            use_program_cache):
        """Per-step path with the run_steps return contract (stacked
        [K, ...] fetches). return_numpy=False per inner step keeps the
        steps pipelining on-device; only the final stack converts."""
        per_step = []
        for k in range(steps):
            f = feeds_seq[k] if feeds_seq is not None else feed
            per_step.append(self.run(
                program, feed=f, fetch_list=fetch_list, scope=scope,
                return_numpy=False, use_program_cache=use_program_cache))
        n_fetch = len(per_step[0]) if per_step else 0
        out = []
        for i in range(n_fetch):
            vals = [r[i] for r in per_step]
            if return_numpy:
                out.append(np.stack([np.asarray(v) for v in vals]))
            else:
                out.append(jnp.stack(vals))
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def prepare_unsupported_reason(program) -> Optional[str]:
        """None when prepare(program) is supported, else the named
        PROGRAM-level reason it is not. Callers with a per-call
        fallback (predictor/serving) check this up front so that
        per-REQUEST errors (bad feed shape) from a prepared handle
        propagate like Executor.run's would, instead of being
        mistaken for 'program not preparable'. Memoized per
        (program, version, native-build flag): hot serving paths ask
        on every request and must not re-walk the op list."""
        from ..flags import FLAGS

        from .compiler import CompiledProgram

        if isinstance(program, CompiledProgram):
            return "CompiledProgram runs through its own path"
        key = (program._uid, program._version, FLAGS.native_build)
        cached = _PREPARE_REASON_CACHE.get(key)
        if cached is not None:
            return cached[0]
        if FLAGS.native_build:
            reason = ("FLAGS_native_build steps carry their own "
                      "context")
        elif any(op.type == "go"
                 for op in program.global_block.ops):
            reason = "`go` ops launch host threads per run"
        else:
            reason = None
        if len(_PREPARE_REASON_CACHE) > 512:
            _PREPARE_REASON_CACHE.clear()
        _PREPARE_REASON_CACHE[key] = (reason,)
        return reason

    def prepare(self, program: Optional[Program] = None, feed=None,
                fetch_list=None, scope: Optional[Scope] = None,
                steps: Optional[int] = None) -> "PreparedProgram":
        """Resolve the executable + binding plans ONCE; the returned
        PreparedProgram.run(feed) is the serving/bench hot-loop entry
        that skips per-call cache hashing and trace-env rebuild
        (reference Executor::Prepare / RunPreparedContext,
        framework/executor.cc:337,377 — there it skips per-step op
        creation; here it skips the Python dispatch prologue, the
        measured 0.8-2.5 ms/step term of PERF.md "Host dispatch").

        `feed` is an EXAMPLE feed dict (arrays at the exact serving
        shapes) or a list of (name, shape, dtype) specs. With
        steps=K the prepared executable is the K-step scan
        (run_steps semantics: one shared feed dict per call, stacked
        [K, ...] fetches; unscannable programs fall back per-step
        with the named reason on `prepared.fallback_reason`)."""
        program = program or default_main_program()
        reason = self.prepare_unsupported_reason(program)
        if reason is not None:
            raise TypeError(f"prepare() does not support this "
                            f"program: {reason}; use Executor.run")
        scope = scope or global_scope()
        return PreparedProgram(self, program, scope, feed, fetch_list,
                               steps=steps)

    # --- in-memory cache keys (ONE builder per kind: run/run_steps/
    # PreparedProgram._bind must agree byte-for-byte or they stop
    # sharing executables) -------------------------------------------
    @staticmethod
    def _block_cache_key(program, feed_specs, fetch_names):
        from .. import amp
        from .sharding_plan import program_sharding_token

        return (program._uid, program._version,
                tuple(sorted(feed_specs)), tuple(fetch_names),
                amp.state_token(), _parallel_scope_token(),
                program_sharding_token(program))

    @staticmethod
    def _scan_cache_key(program, feed_specs, fetch_names, steps,
                        stacked):
        from .. import amp
        from .sharding_plan import program_sharding_token

        return ("scan", program._uid, program._version,
                tuple(sorted(feed_specs)), tuple(fetch_names),
                int(steps), bool(stacked), amp.state_token(),
                _parallel_scope_token(),
                program_sharding_token(program))

    # --- warm-start layer (core/compile_cache.py) ---------------------
    def _disk_slot(self, program, feed_specs, fetch_names, kind,
                   extra=()):
        """(CompileCache, key digest) for one compile site, or
        (None, None) when the disk cache is off / inapplicable. The
        digest is process-STABLE: Program.fingerprint() (not _uid) +
        feed specs + fetch set + AMP/parallel-scope tokens + backend +
        device count + jax/jaxlib versions — any toolchain or program
        change is a clean miss."""
        from ..flags import FLAGS

        if FLAGS.native_build:
            # native-built steps have their own C++ artifact path
            return None, None
        from .compile_cache import (active_cache, canonical_digest,
                                    version_token)

        dcache = active_cache()
        if dcache is None:
            return None, None
        if _first_host_effect_op(program.global_block) is not None:
            # io_callback closures are process-local function
            # pointers: a persisted executable carrying one would
            # crash (or worse) in the fresh process that loads it —
            # host-bridging programs stay process-local, both on
            # store AND on load
            return None, None
        from .. import amp
        from .sharding_plan import program_sharding_token

        parts = {"kind": kind,
                 "program": program.fingerprint(),
                 "feeds": sorted(tuple(s) for s in feed_specs),
                 "fetch": tuple(fetch_names),
                 "amp": amp.state_token(),
                 "pscope": _parallel_scope_token(),
                 # mesh shape + placements + bound device ids: a
                 # sharded and a dense build of one program — or one
                 # plan bound to two different device slices — must
                 # never share a persisted executable
                 "sharding": program_sharding_token(program),
                 "donate": self.donate,
                 "backend": jax.default_backend(),
                 "ndev": jax.device_count(),
                 "extra": tuple(extra)}
        parts.update(version_token())
        return dcache, canonical_digest(parts)

    def _resolve_block(self, program, block, feed_specs, fetch_names,
                       scope, feed_arrays):
        """In-memory-miss path for run(): rehydrate a serialized
        executable from the warm-start cache (ZERO tracing), else
        trace + compile (persisting the result when writable)."""
        t0 = time.monotonic()
        dcache, digest = self._disk_slot(program, feed_specs,
                                         fetch_names, "block")
        if dcache is not None:
            got = dcache.load_executable(digest)
            if got is not None:
                fn, meta = got
                # the pre-compile static-check gate still guards
                # disk-warmed paths (cached per program version)
                from ..analysis import maybe_check_program

                maybe_check_program(program)
                self.disk_load_count += 1
                _record_compile_event("block", program, "disk", t0, fn)
                _note_cost_model(program, fn, "block", feed_specs)
                return _CompiledBlock(
                    fn, tuple(meta["feed_names"]), meta["state_in"],
                    meta["const_in"], meta["state_out"],
                    meta["fetch_names"])
        compiled = self._compile(program, block,
                                 tuple(sorted(feed_arrays)),
                                 fetch_names, scope,
                                 feed_arrays=feed_arrays,
                                 aot=dcache is not None)
        self.compile_count += 1
        _record_compile_event("block", program, "cold", t0,
                              compiled.fn)
        _note_cost_model(program, compiled.fn, "block", feed_specs,
                         compiled=compiled, scope=scope,
                         feed_arrays=feed_arrays)
        if dcache is not None and dcache.writable:
            self._disk_store(dcache, digest, compiled, kind="block",
                             program=program)
        return compiled

    def _resolve_scan(self, program, block, feed_specs, fetch_names,
                      scope, steps, stacked, feed_arrays, device):
        """run_steps analogue of _resolve_block — the K-specialized
        scan executable is the most expensive single compile in the
        repo, so it benefits most from the disk warm start."""
        t0 = time.monotonic()
        dcache, digest = self._disk_slot(program, feed_specs,
                                         fetch_names, "scan",
                                         extra=(steps, stacked))
        if dcache is not None:
            got = dcache.load_executable(digest)
            if got is not None:
                fn, meta = got
                from ..analysis import maybe_check_program

                maybe_check_program(program)
                self.disk_load_count += 1
                _record_compile_event("scan", program, "disk", t0, fn)
                _note_cost_model(program, fn, "scan", feed_specs)
                wos = {n: jax.ShapeDtypeStruct(tuple(shape),
                                               _dtype_from_str(dt))
                       for n, shape, dt in meta["write_only_specs"]}
                return _CompiledScan(
                    fn, tuple(meta["feed_names"]), meta["state_in"],
                    meta["const_in"], meta["state_out"],
                    meta["fetch_names"], wos, meta["steps"],
                    meta["stacked"])
        compiled = self._compile_steps(
            program, block, tuple(sorted(feed_arrays)), fetch_names,
            scope, steps, stacked=stacked, feed_arrays=feed_arrays,
            device=device, aot=dcache is not None)
        self.compile_count += 1
        _record_compile_event("scan", program, "cold", t0,
                              compiled.fn)
        _note_cost_model(program, compiled.fn, "scan", feed_specs,
                         compiled=compiled, scope=scope,
                         feed_arrays=feed_arrays,
                         write_only=compiled.write_only_specs)
        if dcache is not None and dcache.writable:
            self._disk_store(
                dcache, digest, compiled, kind="scan",
                program=program,
                extra_meta={
                    "write_only_specs": [
                        (n, tuple(s.shape), str(s.dtype))
                        for n, s in
                        compiled.write_only_specs.items()],
                    "steps": steps, "stacked": stacked})
        return compiled

    def _disk_store(self, dcache, digest, compiled, kind,
                    extra_meta=None, program=None):
        """Persist a freshly AOT-compiled executable + the binding
        metadata a future process needs to rehydrate it untraced."""
        aot = getattr(compiled, "_aot", None)
        if aot is None:
            return  # AOT lowering was unavailable (e.g. uninit state)
        lowered, in_avals, out_shape = aot
        meta = {"kind": kind,
                "feed_names": list(compiled.feed_names),
                "state_in": list(compiled.state_in),
                "const_in": list(compiled.const_in),
                "state_out": list(compiled.state_out),
                "fetch_names": list(compiled.fetch_names),
                "in_avals": in_avals}
        if program is not None:
            from .sharding_plan import plan_of

            plan = plan_of(program)
            if plan is not None and plan.is_bound:
                # rehydration context check (compile_cache): a sharded
                # executable embeds its device assignment — loading
                # it on a process whose mesh devices do not exist must
                # be a NAMED discard, not a deserialization crash
                meta["mesh"] = {"ndev": plan.n_devices,
                                "axes": list(plan.axes),
                                "device_ids": list(plan._device_ids)}
        if extra_meta:
            meta.update(extra_meta)
        dcache.store_executable(digest, compiled.fn, lowered,
                                out_shape, meta)

    @staticmethod
    def _plan_jit_shardings(program, block, carry_names, const,
                            state_out, fetch_names, scan=False):
        """(in_shardings, out_shardings) for a sharded program's jit
        boundary, or None for unsharded/unbound programs. Entry AND
        result shardings of every persistable are pinned to the
        plan's placement, so donated state round-trips with a
        byte-stable layout and prepared handles never re-specialize
        mid-traffic (the zero-steady-state-compiles contract); feeds
        and the rng are replicated on the mesh (numpy feeds are
        device_put per call by the dispatch path — host-written
        block tables stay plain numpy on the host side)."""
        from .sharding_plan import plan_of

        plan = plan_of(program)
        if plan is None or not plan.is_bound:
            return None

        def sh(name):
            v = block._find_var_recursive(name)
            shape = tuple(v.shape) if v is not None \
                and v.shape is not None else None
            return plan.sharding_for(name, shape)

        repl = plan.replicated()
        in_sh = ({n: sh(n) for n in carry_names},
                 {n: sh(n) for n in const},
                 repl,   # feeds dict (pytree prefix)
                 repl)   # rng
        # scan fetches are stacked [K, ...]: placement dims would be
        # off by one — replicate them (fetches are host readbacks)
        fetch_sh = [repl if scan else sh(n) for n in fetch_names]
        out_sh = ({n: sh(n) for n in state_out}, fetch_sh, repl)
        return in_sh, out_sh

    def _try_aot(self, jitted, fn, example_args):
        """Lower + compile ahead-of-time so the executable can be
        serialized (jax.jit's lazy path never exposes the Compiled).
        Returns (compiled_fn, (lowered, in_avals, out_shape)) or None
        to fall back to plain jit — never raises."""
        try:
            in_avals = jax.tree.map(_as_aval, example_args)
            lowered = jitted.lower(*in_avals)
            compiled = lowered.compile()
            out_shape = getattr(lowered, "out_info", None)
            if out_shape is None:
                out_shape = jax.eval_shape(fn, *in_avals)
            return compiled, (lowered, in_avals, out_shape)
        except Exception as e:
            import warnings

            warnings.warn(
                f"compile_cache: AOT lowering failed "
                f"({type(e).__name__}: {e}); this executable stays "
                f"process-local")
            return None

    # ------------------------------------------------------------------
    def _compile_steps(self, program, block, feed_names, fetch_names,
                       scope, steps, stacked, feed_arrays, device,
                       aot=False):
        """Lower the SAME _build_step_fn body run() compiles -- the
        step-key advance included -- into one jitted lax.scan over K
        steps with donated carry state."""
        nprog = None
        if _native_usable(block):
            try:
                nprog = _native_prog(block)
            except Exception:
                nprog = None
        mutated, const, state_out = _analyze_block(
            block, feed_names, fetch_names, nprog=nprog)
        free_after = _last_use_plan(block, feed_names, fetch_names,
                                    nprog=nprog)
        step = _build_step_fn(block, feed_names, mutated, const,
                              state_out, fetch_names,
                              free_after=free_after)
        mutated_set = set(mutated)
        write_only = [n for n in state_out if n not in mutated_set]

        def multi(carry_state, const_state, feeds, rng):
            def body(carry, xs):
                state, key = carry
                mut = {n: state[n] for n in mutated}
                f = xs if stacked else feeds
                new_state, fetches, key = step(mut, const_state, f,
                                               key)
                nxt = dict(state)
                nxt.update(new_state)
                return (nxt, key), fetches

            (fin, key_out), ys = jax.lax.scan(
                body, (carry_state, rng),
                xs=feeds if stacked else None,
                length=None if stacked else steps)
            return fin, ys, key_out

        # shapes of the write-only carry slots come from one abstract
        # eval of the single step (dtypes canonicalized the way jit
        # will see them)
        mut_ex = self._scope_state(scope, mutated, device)
        const_ex = self._scope_state(scope, const, device)
        rng_ex = scope._get(RNG_VAR)
        if rng_ex is None:
            rng_ex = jax.random.PRNGKey(0)
        write_only_specs = {}
        if write_only:
            if stacked:
                feeds_ex = {
                    n: jax.ShapeDtypeStruct(tuple(a.shape[1:]), a.dtype)
                    for n, a in feed_arrays.items()}
            else:
                feeds_ex = {
                    n: jax.ShapeDtypeStruct(
                        tuple(a.shape),
                        jax.dtypes.canonicalize_dtype(a.dtype))
                    for n, a in feed_arrays.items()}
            new_state_shapes = jax.eval_shape(
                step, mut_ex, const_ex, feeds_ex, rng_ex)[0]
            write_only_specs = {n: new_state_shapes[n]
                                for n in write_only}
        carry_ex = dict(mut_ex)
        for n, spec in write_only_specs.items():
            carry_ex[n] = jnp.zeros(spec.shape, spec.dtype)
        donate = (0,) if self.donate else ()
        carry_names = list(mutated) + list(write_only_specs)
        plan_sh = self._plan_jit_shardings(program, block, carry_names,
                                           const, carry_names,
                                           fetch_names, scan=True)
        if plan_sh is not None:
            jitted = jax.jit(multi, donate_argnums=donate,
                             in_shardings=plan_sh[0],
                             out_shardings=plan_sh[1])
        else:
            layouts = _pin_state_layout_formats(
                multi, carry_ex, const_ex, feed_arrays, rng_ex,
                self.place)
            if layouts is not None:
                jitted = jax.jit(multi, donate_argnums=donate,
                                 in_shardings=layouts[0],
                                 out_shardings=layouts[1])
            else:
                jitted = jax.jit(multi, donate_argnums=donate)
        fn = jitted
        aot_art = None
        if aot:
            got = self._try_aot(
                jitted, multi,
                (carry_ex, const_ex, dict(feed_arrays), rng_ex))
            if got is not None:
                fn, aot_art = got
        scan = _CompiledScan(fn, feed_names, mutated, const,
                             state_out, fetch_names, write_only_specs,
                             steps, stacked)
        if aot_art is not None:
            scan._aot = aot_art
        return scan

    # ------------------------------------------------------------------
    def _compile(self, program, block, feed_names, fetch_names, scope,
                 feed_arrays=None, aot=False):
        # build the native program once; both analyses share it
        nprog = None
        if _native_usable(block):
            try:
                nprog = _native_prog(block)
            except Exception:
                nprog = None
        mutated, const, state_out = _analyze_block(
            block, feed_names, fetch_names, nprog=nprog)
        free_after = _last_use_plan(block, feed_names, fetch_names,
                                    nprog=nprog)
        step = _build_step_fn(block, feed_names, mutated, const, state_out,
                              fetch_names, free_after=free_after)
        donate = (0,) if self.donate else ()
        plan_sh = self._plan_jit_shardings(program, block, mutated,
                                           const, state_out,
                                           fetch_names)
        if plan_sh is not None:
            jitted = jax.jit(step, donate_argnums=donate,
                             in_shardings=plan_sh[0],
                             out_shardings=plan_sh[1])
        else:
            layouts = _default_layout_specs(
                step, scope, mutated, const, feed_arrays, self.place)
            if layouts is not None:
                jitted = jax.jit(step, donate_argnums=donate,
                                 in_shardings=layouts[0],
                                 out_shardings=layouts[1])
            else:
                jitted = jax.jit(step, donate_argnums=donate)
        fn = jitted
        aot_art = None
        if aot:
            mut_ex = {n: scope._get(n) for n in mutated}
            const_ex = {n: scope._get(n) for n in const}
            if not (any(v is None for v in mut_ex.values())
                    or any(v is None for v in const_ex.values())):
                # uninitialized state: skip AOT, run() raises the
                # friendly init error on the plain path
                rng_ex = scope._get(RNG_VAR)
                if rng_ex is None:
                    rng_ex = jax.random.PRNGKey(0)
                got = self._try_aot(
                    jitted, step,
                    (mut_ex, const_ex, dict(feed_arrays or {}),
                     rng_ex))
                if got is not None:
                    fn, aot_art = got
        blk = _CompiledBlock(fn, feed_names, mutated, const, state_out,
                             fetch_names)
        if aot_art is not None:
            blk._aot = aot_art
        return blk

    # fluid parity helper: infer feed order from a program's data vars
    def _feed_data_names(self, program):
        return [v.name for v in program.global_block.vars.values()
                if v.is_data]


class PreparedProgram:
    """Prepared-dispatch fast path (reference ExecutorPrepareContext:
    Executor::Prepare builds the op list once, RunPreparedContext
    replays it, framework/executor.cc:337,377).

    Binds ONCE: the resolved executable (through the same in-memory /
    on-disk caches as Executor.run, so a warmed bucket is shared), the
    feed order + coercion dtypes, the scope-gather name lists, and the
    device commitment. `run(feed)` then goes straight from feed dict
    to executable call — no fetch parsing, no key hashing, no feed
    validation, no block analysis.

    Staleness guards stay cheap but present: every run() compares the
    program `_version` (Pass.apply bumps it) and the AMP /
    parallel-scope tokens against the bound snapshot and re-binds on
    change — a prepared handle can never serve a stale executable.
    Feed arrays must match the prepared (shape, dtype) specs exactly;
    new shapes need a new prepare() (or Executor.run, which
    re-specializes per call)."""

    def __init__(self, exe: Executor, program: Program, scope: Scope,
                 feed, fetch_list, steps: Optional[int] = None):
        self.exe = exe
        self.program = program
        self.scope = scope
        self.fetch_names = _to_fetch_names(fetch_list)
        self._steps = int(steps) if steps is not None else None
        if self._steps is not None and self._steps < 1:
            raise ValueError(
                f"prepare: steps must be >= 1, got {steps}")
        if isinstance(feed, (list, tuple)):
            # [(name, shape, dtype)] specs -> synthetic example arrays
            feed = {name: np.zeros(tuple(shape), _dtype_from_str(dt))
                    for name, shape, dt in feed}
        self._feed_example = dict(feed or {})
        self._bind_specs = None
        self._bind()

    @property
    def fallback_reason(self) -> Optional[str]:
        """Named reason the prepared scan runs per-step (None = the
        K-step scan executable is bound)."""
        return self._fallback_reason

    def _snapshot_tokens(self):
        from .. import amp

        self._pversion = self.program._version
        self._amp_tok = amp.state_token()
        self._ptok = _parallel_scope_token()

    def _bind(self):
        exe, program, scope = self.exe, self.program, self.scope
        block = program.global_block
        if self._feed_example is None:
            # a re-bind (version/AMP change): the original example
            # arrays were dropped after the first bind (a prepared
            # training batch can be large device memory); zeros at
            # the recorded specs are shape/dtype-equivalent
            self._feed_example = {
                name: np.zeros(shape, _dtype_from_str(dt))
                for name, shape, dt in self._bind_specs}
        for name in self.fetch_names:
            if not block.has_var(name) \
                    and name not in self._feed_example:
                raise KeyError(
                    f"fetch target {name!r} does not exist in the "
                    f"program")
        self._fallback_reason = None
        if self._steps is not None:
            reason = _scan_fallback_reason(program)
            if reason is not None:
                self._fallback_reason = reason
                exe._warn_scan_fallback(program, reason)
                self._snapshot_tokens()
                return
        try:
            device = exe.place.device()
        except Exception:
            device = None
        if device is not None and jax.device_count() > 1:
            device = None  # same multi-device caveat as run()
        self._device = device

        feed_arrays = {}
        feed_specs = []
        np_dtypes = {}
        for name, val in self._feed_example.items():
            dt = _var_np_dtype(block, name)
            np_dtypes[name] = dt
            arr = _coerce_feed(val, dt)
            _check_feed_shape(block, name, arr)
            if device is not None and not isinstance(arr, jax.Array):
                arr = jax.device_put(arr, device)
            feed_arrays[name] = arr
            feed_specs.append((name, tuple(arr.shape),
                               str(arr.dtype)))
        # the same in-memory keys run()/run_steps() use (one shared
        # builder per kind), so prepared handles, plain runs, and
        # serving clones share executables
        if self._steps is None:
            key = exe._block_cache_key(program, feed_specs,
                                       self.fetch_names)
            compiled = exe._cache.get(key)
            if compiled is None:
                compiled = exe._resolve_block(
                    program, block, tuple(sorted(feed_specs)),
                    self.fetch_names, scope, feed_arrays)
                exe._cache[key] = compiled
            else:
                exe.cache_hit_count += 1
        else:
            key = exe._scan_cache_key(program, feed_specs,
                                      self.fetch_names, self._steps,
                                      False)
            compiled = exe._cache.get(key)
            if compiled is None:
                compiled = exe._resolve_scan(
                    program, block, tuple(sorted(feed_specs)),
                    self.fetch_names, scope, self._steps, False,
                    feed_arrays, device)
                exe._cache[key] = compiled
            else:
                exe.cache_hit_count += 1
        self._compiled = compiled
        self._np_dtypes = {n: np_dtypes.get(n, _var_np_dtype(block, n))
                           for n in compiled.feed_names}
        # spec check table: shapes strict, dtypes compared AFTER
        # canonicalization so a numpy-int64 example and a jax-int32
        # array at run time agree (jit canonicalizes both the same)
        self._check_specs = {
            name: (shape,
                   str(jax.dtypes.canonicalize_dtype(
                       _dtype_from_str(dt))))
            for name, shape, dt in feed_specs}
        self._bind_specs = feed_specs
        self._feed_example = None  # large batches must not be pinned
        # for the handle's lifetime; re-binds rebuild from specs
        self._snapshot_tokens()

    def run(self, feed=None, return_numpy: bool = True):
        """The hot loop. Semantics match Executor.run (or run_steps
        when prepared with steps=K) exactly, minus per-call shape
        re-validation."""
        exe = self.exe
        from .. import amp
        from ..flags import FLAGS

        if (self.program._version != self._pversion
                or amp.state_token() != self._amp_tok
                or _parallel_scope_token() != self._ptok):
            self._bind()  # Pass.apply / AMP toggle / scope change:
            # re-resolve instead of serving a stale executable
        else:
            # observability parity with Executor.run: a prepared call
            # served from the bound executable is a cache hit (the
            # serving stats/tests count hits per request)
            exe.cache_hit_count += 1
        if self._fallback_reason is not None:
            exe.last_run_steps_fallback = self._fallback_reason
            return exe._run_steps_fallback(
                self.program, dict(feed or {}), None,
                list(self.fetch_names), self._steps, self.scope,
                return_numpy, True)
        if self._steps is not None:
            exe.last_run_steps_fallback = None
        c = self._compiled
        scope, device = self.scope, self._device
        feed = feed or {}
        if set(feed) != set(c.feed_names):
            unknown = sorted(set(feed) - set(c.feed_names))
            missing = sorted(set(c.feed_names) - set(feed))
            raise ValueError(
                f"prepared program binds feeds "
                f"{sorted(c.feed_names)}; got unknown={unknown} "
                f"missing={missing}")
        feed_arrays = {}
        for name in c.feed_names:
            arr = _coerce_feed(feed[name], self._np_dtypes[name])
            want_shape, want_dt = self._check_specs[name]
            got_dt = str(jax.dtypes.canonicalize_dtype(arr.dtype))
            if tuple(arr.shape) != want_shape or got_dt != want_dt:
                raise ValueError(
                    f"prepared program was bound for feed {name!r} "
                    f"spec {want_shape}/{want_dt} but got "
                    f"{tuple(arr.shape)}/{got_dt}; prepare() again "
                    f"for new shapes (or use Executor.run)")
            if device is not None and not isinstance(arr, jax.Array):
                arr = jax.device_put(arr, device)
            feed_arrays[name] = arr

        mut = exe._scope_state(scope, c.state_in, device)
        const_st = exe._scope_state(scope, c.const_in, device)
        rng = scope._get(RNG_VAR)
        if rng is None:
            prog_seed = getattr(self.program, "_seed", None)
            rng = jax.random.PRNGKey(
                prog_seed if prog_seed is not None
                else _global_seed[0])
        if isinstance(c, _CompiledScan):
            for n, spec in c.write_only_specs.items():
                mut[n] = jnp.zeros(spec.shape, spec.dtype)
        new_state, out, rng_out = c.fn(mut, const_st, feed_arrays,
                                       rng)
        if FLAGS.check_nan_inf:
            _check_nan_inf(new_state, out, c.fetch_names)
        scope._set(RNG_VAR, rng_out)
        for n, v in new_state.items():
            scope._set(n, v)
        if return_numpy:
            return [np.asarray(v) for v in out]
        return list(out)


class PreparedCache:
    """Feed-spec-keyed LRU of PreparedProgram handles — the shared
    serving-hot-loop helper behind AnalysisPredictor._run_feed and
    serving.ProgramRunner.run_batch (reference analogue: the
    predictor holding one prepared ctx per input signature around
    Executor::RunPreparedContext, executor.cc:337).

    Capped so unbucketed many-shape traffic cannot pin one executable
    per transient shape forever (the leak class
    FLAGS_executor_cache_capacity closes, one layer up)."""

    def __init__(self, executor: Executor, program, fetch_names,
                 scope, capacity: int = 32):
        self._exe = executor
        self._program = program
        self._fetch_names = list(fetch_names)
        self._scope = scope
        self._cap = int(capacity)
        self._d: "collections.OrderedDict" = collections.OrderedDict()

    def lookup(self, feed) -> Optional["PreparedProgram"]:
        """The PreparedProgram for this feed's spec, binding it on
        first sight, or None when the program takes the per-call
        Executor.run path (go ops / CompiledProgram / native build —
        checked up front so a per-REQUEST feed error raises exactly
        like Executor.run's validation would). Normalizes non-array
        feed values in place."""
        if Executor.prepare_unsupported_reason(self._program) \
                is not None:
            return None
        key = []
        for n in sorted(feed):
            v = feed[n]
            if not hasattr(v, "shape") or callable(
                    getattr(v, "shape", None)):
                v = feed[n] = np.asarray(v)
            key.append((n, tuple(v.shape), str(v.dtype)))
        key = tuple(key)
        prepared = self._d.get(key)
        if prepared is not None:
            self._d.move_to_end(key)  # LRU recency
            return prepared
        prepared = self._exe.prepare(
            self._program, feed, fetch_list=self._fetch_names,
            scope=self._scope)
        self._d[key] = prepared
        while len(self._d) > self._cap:
            self._d.popitem(last=False)
        return prepared

    def __len__(self):
        return len(self._d)


def _to_fetch_names(fetch_list) -> List[str]:
    names = []
    if fetch_list is None:
        return names
    if not isinstance(fetch_list, (list, tuple)):
        fetch_list = [fetch_list]
    for f in fetch_list:
        if isinstance(f, Variable):
            names.append(f.name)
        elif isinstance(f, str):
            names.append(f)
        else:
            raise TypeError(f"bad fetch entry: {f!r}")
    return names


def _coerce_feed(val, np_dtype):
    if isinstance(val, tuple) and len(val) == 2:
        # (data, lod) legacy feed -- LoD handled by sequence ops via
        # explicit segment inputs; dense part fed here.
        val = val[0]
    if isinstance(val, jax.Array):
        # already device-resident (e.g. a reader that pre-transfers);
        # keep it -- re-materializing via numpy would force a d2h+h2d
        return val
    arr = np.asarray(val)
    if np_dtype is not None and arr.dtype != np_dtype \
            and np.issubdtype(arr.dtype, np.floating) \
            == np.issubdtype(np_dtype, np.floating):
        arr = arr.astype(np_dtype)
    return arr
