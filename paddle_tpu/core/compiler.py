"""CompiledProgram / ParallelExecutor: multi-device data-parallel
compilation.

TPU-native replacement for the reference's ParallelExecutor machinery
(reference: framework/parallel_executor.cc:184, details/build_strategy.cc:
50-195, details/multi_devices_graph_pass.cc, all_reduce_op_handle.cc:298).

Where the reference replicates the op graph per GPU and schedules
ncclAllReduce per gradient at runtime through an SSA executor, here
with_data_parallel() jit-compiles the SAME block function over a
jax.sharding.Mesh: feeds are sharded batch-wise, params replicated, and
gradient all-reduce is *inside* the XLA program (psum over ICI), which
also subsumes fuse_all_reduce_ops / alloc_continuous_space_for_grad --
XLA coalesces collectives itself.

BuildStrategy/ExecutionStrategy keep the reference's knob surface; knobs
that XLA makes obsolete are accepted and recorded (harmless no-ops) so
user scripts run unchanged.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .executor import (RNG_VAR, _analyze_block, _build_step_fn,
                       _coerce_feed, _to_fetch_names, _var_np_dtype,
                       _global_seed)
from .program import Program, default_main_program
from .scope import global_scope


class ExecutionStrategy:
    """reference details/execution_strategy.h:22."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.allow_op_delay = False
        self.use_experimental_executor = False


class BuildStrategy:
    """reference details/build_strategy.h:35."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = False
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_all_optimizer_ops = False
        self.fuse_relu_depthwise_conv = False
        self.sync_batch_norm = False
        self.enable_parallel_graph = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.remove_unnecessary_lock = True


class CompiledProgram:
    """reference python/paddle/fluid/compiler.py:48."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program: Program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._is_inference = False
        self._loss_name = None
        self._share_vars_from = None
        self._places = None
        self._cache: Dict = {}

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, mesh=None,
                           sharding_rules="auto", n_micro=None,
                           pp_schedule="gpipe"):
        """`mesh` (optional): a jax Mesh whose axes may include 'tp'
        (and other non-'dp' axes of size 1) so data parallelism
        COMPOSES with tensor parallelism from the user API (VERDICT r2
        weak #6) — params are then placed by the structural rules read
        off the program graph (parallel/sharding.py
        derive_sharding_rules), or by an explicit `sharding_rules`
        object. Without `mesh`, the classic 1-axis dp mesh over
        `places` is used and params are replicated."""
        self._is_data_parallel = True
        self._loss_name = loss_name.name \
            if hasattr(loss_name, "name") else loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        self._user_mesh = mesh
        self._sharding_rules = sharding_rules
        # placement-config epoch: id()-keyed cache entries would be
        # unsound (a GC'd mesh/rules object's address can be reused);
        # every reconfigure bumps this instead
        self._config_epoch = getattr(self, "_config_epoch", 0) + 1
        self._n_micro = n_micro
        self._pp_schedule = pp_schedule
        pp = 1
        if mesh is not None and hasattr(mesh, "shape"):
            pp = mesh.shape.get("pp", 1)
        if mesh is not None and "dp" not in mesh.axis_names and pp <= 1:
            raise ValueError(
                "with_data_parallel(mesh=...) needs a 'dp' axis (or a "
                f"'pp' axis > 1 for pipeline runs); got axes "
                f"{mesh.axis_names}")
        if pp > 1 and loss_name is None:
            raise ValueError(
                "with_data_parallel over a 'pp' mesh needs loss_name "
                "(the pipeline schedule differentiates through to it)")
        if self._build_strategy.fuse_all_optimizer_ops:
            # reference build_strategy.cc appends fuse_adam/sgd passes
            # when this knob is on; same pipeline here (ir.py)
            from ..ir import apply_passes

            apply_passes(self._program,
                         ["fuse_adam_op_pass", "fuse_sgd_op_pass"])
        return self

    def with_inference_optimize(self, config):
        self._is_inference = True
        return self

    # ------------------------------------------------------------------
    def _mesh(self):
        if getattr(self, "_user_mesh", None) is not None:
            return self._user_mesh
        devs = self._places
        if devs is None or not len(devs):
            devices = jax.devices()
        else:
            all_dev = jax.devices()
            devices = [all_dev[getattr(p, "device_id", i) % len(all_dev)]
                       for i, p in enumerate(devs)]
        return Mesh(np.array(devices), ("dp",))

    def _param_rules(self):
        """Param placement rules for a composed mesh (None = replicate
        everything, the classic dp behavior). Auto-derived rules are
        cached per program VERSION: a Pass that mutates the program
        (and bumps _version) gets a fresh structural table, not a
        stale one missing its new params."""
        mesh = self._mesh()
        tp = mesh.shape.get("tp", 1) if hasattr(mesh, "shape") else 1
        if tp <= 1:
            return None
        rules = getattr(self, "_sharding_rules", "auto")
        if isinstance(rules, str) and rules == "auto":
            ver = self._program._version
            cached = getattr(self, "_auto_rules", None)
            if cached is None or cached[0] != ver:
                from ..parallel.sharding import derive_sharding_rules

                self._auto_rules = (
                    ver, derive_sharding_rules(self._program))
            return self._auto_rules[1]
        return rules

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        if not self._is_data_parallel:
            return executor.run(self._program, feed=feed,
                                fetch_list=fetch_list, scope=scope,
                                return_numpy=return_numpy)
        scope = scope or global_scope()
        feed = dict(feed or {})
        fetch_names = _to_fetch_names(fetch_list)
        block = self._program.global_block
        mesh = self._mesh()
        if hasattr(mesh, "shape") and mesh.shape.get("pp", 1) > 1:
            # pipeline mesh: GPipe by default, 1F1B via
            # pp_schedule='1f1b' (parallel/pipeline_1f1b.py) —
            # reachable through the SAME user API as dp x tp
            # (VERDICT r3 weak #4: PP must not be a side-car object)
            return self._run_pipeline(feed, fetch_names, scope, mesh,
                                      return_numpy)
        ndev = mesh.shape.get("dp", 1) if hasattr(mesh, "shape") \
            else mesh.devices.size

        feed_arrays = {}
        feed_specs = []
        for name, val in feed.items():
            arr = _coerce_feed(val, _var_np_dtype(block, name))
            if arr.shape[0] % ndev != 0:
                # drop remainder like fluid's ParallelExecutor feed split
                arr = arr[: (arr.shape[0] // ndev) * ndev]
            feed_arrays[name] = arr
            feed_specs.append((name, arr.shape, str(arr.dtype)))
        from .. import amp
        from .executor import _parallel_scope_token

        key = (self._program._uid, self._program._version,
               tuple(sorted(feed_specs)), tuple(fetch_names), ndev,
               getattr(self, "_config_epoch", 0),
               amp.state_token(), _parallel_scope_token())
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = self._compile(block, tuple(sorted(feed_arrays)),
                                     fetch_names, mesh)
            self._cache[key] = compiled
        return compiled(scope, feed_arrays, return_numpy)

    def _run_pipeline(self, feed, fetch_names, scope, mesh,
                      return_numpy):
        from ..parallel.pipeline_program import (PipelineTrainer,
                                                 PipelinePartitionError,
                                                 propose_loops)

        epoch = getattr(self, "_config_epoch", 0)
        ver = self._program._version
        hints = tuple(sorted(
            n for n in fetch_names if n != self._loss_name))
        tr = getattr(self, "_pp_trainer", None)

        def build(hint_set):
            loops = propose_loops(self._program, self._loss_name)
            if not loops:
                raise PipelinePartitionError(
                    "no repeated-layer loops detected in the program; "
                    "a pipeline mesh needs at least one isomorphic "
                    "layer stack (pass a deeper model or drop the "
                    "'pp' axis)")
            pp = mesh.shape.get("pp", 1)
            n_micro = getattr(self, "_n_micro", None) or 2 * pp
            rules = getattr(self, "_sharding_rules", "auto")
            t = PipelineTrainer(self._program, self._loss_name,
                                loops=loops, mesh=mesh,
                                n_micro=n_micro,
                                tp_rules=None if isinstance(rules, str)
                                else rules,
                                schedule=getattr(
                                    self, "_pp_schedule", "gpipe"),
                                fetch_hints=hint_set)
            t.initialize(scope)
            return t

        if tr is None or self._pp_key[:3] != (epoch, ver, scope._uid):
            tr = build(hints)
            self._pp_trainer = tr
            self._pp_key = (epoch, ver, scope._uid, hints)
        from ..parallel.pipeline_program import PipelineFetchError

        try:
            out = tr.run(feed, fetch_list=fetch_names,
                         return_numpy=return_numpy)
        except PipelineFetchError:
            # a fetch the current partition does not materialize: if
            # NEW hint names appeared, rebuild once with them promoted
            # to reduce outputs (loop-internal observables); otherwise
            # the error is real. State is safe to rebuild from the
            # scope: every prior run wrote back.
            merged = tuple(sorted(set(self._pp_key[3]) | set(hints)))
            if merged == self._pp_key[3]:
                raise
            tr = build(merged)
            self._pp_trainer = tr
            self._pp_key = (epoch, ver, scope._uid, merged)
            out = tr.run(feed, fetch_list=fetch_names,
                         return_numpy=return_numpy)
        loss_val = out[0]
        if return_numpy:
            loss_val = np.asarray(loss_val).reshape(1)  # Executor shape
        tr.write_back(scope)
        results = []
        rest = iter(out[1:])
        for name in fetch_names:
            if name == tr.loss_name:
                results.append(loss_val)
            else:
                results.append(next(rest))
        return results

    def _compile(self, block, feed_names, fetch_names, mesh):
        mutated, const, state_out = _analyze_block(block, feed_names,
                                                   fetch_names)
        step = _build_step_fn(block, feed_names, mutated, const,
                              state_out, fetch_names)
        repl = NamedSharding(mesh, P())
        batched = NamedSharding(mesh, P("dp"))
        rules = self._param_rules()

        def param_sharding(name, val):
            if rules is None:
                return repl
            from ..parallel.sharding import safe_spec

            shape = getattr(val, "shape", ())
            spec = safe_spec(mesh, rules.spec_for(name, len(shape)),
                             shape, name=name)
            return NamedSharding(mesh, spec)
        # No explicit loss scaling needed: the program computes the GLOBAL
        # batch mean, so XLA's SPMD partitioner inserts the psum with the
        # right coefficient -- fluid's CoeffNumDevice scale_loss_grad op
        # (details/scale_loss_grad_op_handle.cc) is subsumed.
        jitted = jax.jit(step, donate_argnums=(0,))
        # rules and mesh are fixed for this executable: memoize each
        # name's target sharding so the steady state pays one dict hit
        # + an is_equivalent_to check per array, not a spec_for
        # key-scan + regex + NamedSharding build per step
        _targets: Dict[str, NamedSharding] = {}

        def run(scope, feed_arrays, return_numpy):
            mut = {n: scope._get(n) for n in mutated}
            const_st = {n: scope._get(n) for n in const}
            for n, v in list(mut.items()) + list(const_st.items()):
                if v is None:
                    raise RuntimeError(
                        f"Variable {n!r} used before initialization -- "
                        f"run the startup program first")
            # place feeds sharded over dp, params replicated
            sharded_feeds = {
                n: jax.device_put(v, batched)
                for n, v in feed_arrays.items()}

            def place(n, v):
                # A previously-placed array is kept only if its sharding
                # agrees with the CURRENT rules: after a reconfiguring
                # with_data_parallel() call the new structural rules must
                # apply to state placed under the old config too (the
                # config epoch busts the executable cache, but the scope
                # arrays live on).
                target = _targets.get(n)
                if target is None:
                    target = _targets[n] = param_sharding(n, v)
                if _is_sharded(v):
                    eq = _sharding_matches(v, target)
                    if eq:
                        return v
                    if eq is None:
                        # the CHECK failed, not the placement: keeping
                        # the array could silently run with a stale
                        # sharding (VERDICT r4 weak #6) — warn and
                        # re-place (device_put is a no-op when the
                        # sharding already agrees)
                        import warnings

                        warnings.warn(
                            f"sharding equivalence check failed for "
                            f"{n!r}; re-placing it under the current "
                            f"rules")
                return jax.device_put(v, target)

            mut = {n: place(n, v) for n, v in mut.items()}
            const_st = {n: place(n, v) for n, v in const_st.items()}
            rng = scope._get(RNG_VAR)
            if rng is None:
                rng = jax.random.PRNGKey(_global_seed[0])
            if not _is_sharded(rng):
                rng = jax.device_put(rng, repl)
            with mesh:
                new_state, fetches, rng_out = jitted(
                    mut, const_st, sharded_feeds, rng)
            scope._set(RNG_VAR, rng_out)
            for n, v in new_state.items():
                scope._set(n, v)
            if return_numpy:
                return [np.asarray(v) for v in fetches]
            return list(fetches)

        return run


def _sharding_matches(v, target):
    """True/False from the equivalence check; None when the check
    itself fails (exotic sharding types) — callers treat None as
    'unknown' and re-place with a warning instead of silently keeping
    a possibly stale-sharded array."""
    try:
        return bool(v.sharding.is_equivalent_to(target, v.ndim))
    except Exception:
        return None


def _is_sharded(v):
    return hasattr(v, "sharding") and getattr(
        v.sharding, "spec", None) is not None and any(
        s is not None for s in getattr(v.sharding, "spec", ()))


class ParallelExecutor:
    """Legacy fluid.ParallelExecutor facade
    (reference python/paddle/fluid/parallel_executor.py)."""

    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .executor import Executor, TPUPlace

        self._program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            self._program, build_strategy).with_data_parallel(
            loss_name=loss_name, exec_strategy=exec_strategy,
            share_vars_from=share_vars_from and
            share_vars_from._compiled)
        self._exe = Executor(TPUPlace())
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)

    @property
    def device_count(self):
        return len(jax.devices())
