"""Core type system for paddle_tpu.

TPU-native analogue of the reference's dtype/vartype enums
(reference: paddle/fluid/framework/framework.proto:91-135 VarType).
We map framework dtypes directly onto JAX/numpy dtypes; bfloat16 is a
first-class citizen (TPU MXU native precision).
"""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class VarType(enum.Enum):
    """Variable kinds (reference framework.proto:108-134)."""

    LOD_TENSOR = "lod_tensor"          # dense tensor (+ optional LoD metadata)
    SELECTED_ROWS = "selected_rows"    # sparse row-set (embedding grads)
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    STEP_SCOPES = "step_scopes"
    READER = "reader"
    RAW = "raw"


class DataType(enum.Enum):
    """Framework dtypes (reference framework.proto:91-106)."""

    BOOL = "bool"
    INT8 = "int8"
    UINT8 = "uint8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FP16 = "float16"
    BF16 = "bfloat16"
    FP32 = "float32"
    FP64 = "float64"


_TO_JNP = {
    DataType.BOOL: jnp.bool_,
    DataType.INT8: jnp.int8,
    DataType.UINT8: jnp.uint8,
    DataType.INT16: jnp.int16,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.FP16: jnp.float16,
    DataType.BF16: jnp.bfloat16,
    DataType.FP32: jnp.float32,
    DataType.FP64: jnp.float64,
}

_FROM_STR = {dt.value: dt for dt in DataType}
_FROM_STR.update({
    "float": DataType.FP32,
    "double": DataType.FP64,
    "half": DataType.FP16,
    "int": DataType.INT32,
    "long": DataType.INT64,
})


def as_datatype(dtype) -> DataType:
    """Coerce a string / numpy dtype / DataType into DataType."""
    if isinstance(dtype, DataType):
        return dtype
    if isinstance(dtype, str):
        if dtype in _FROM_STR:
            return _FROM_STR[dtype]
        return DataType(np.dtype(dtype).name)
    if dtype is bool:
        return DataType.BOOL
    name = jnp.dtype(dtype).name
    return _FROM_STR[name]


def to_jnp_dtype(dtype):
    """Framework dtype -> jnp dtype."""
    return _TO_JNP[as_datatype(dtype)]


def to_np_dtype(dtype):
    dt = as_datatype(dtype)
    if dt == DataType.BF16:
        import ml_dtypes  # shipped with jax

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dt.value)
