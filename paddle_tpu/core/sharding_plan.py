"""ShardingPlan: the mesh-execution half of a sharded program.

Reference counterpart: reference
transpiler/distribute_transpiler.py:69 VarBlock / :1131
_init_splited_vars sliced parameters across pservers by REWRITING the
program; here the program is untouched — a plan is a {persistable/feed
name -> {tensor dim -> mesh axis}} placement table attached to a
Program (``attach``), and the Executor turns it into
``jax.jit(in_shardings=..., out_shardings=...)`` over a named
``jax.sharding.Mesh`` so XLA GSPMD inserts the collectives
(SNIPPETS.md [1]/[3]'s ``Mesh + NamedSharding`` pattern).

Design contract (why this is a separate object, not executor logic):

* The SAME placement dict feeds THREE consumers that must never
  drift: the static prover (``absint.mark_sharded`` annotations are
  emitted from it at build time — PTA130/131/160/161 prove the serve
  While branch-free of misplaced collectives), the runtime
  (``sharding_for`` → NamedSharding for jit boundaries and
  ``place_state`` device_puts), and the cache keys
  (``token()`` joins the executor's in-memory keys, the disk compile
  cache digest, and ``server_fingerprint`` — a sharded and a dense
  build of one program must never dedupe, and a warm-start entry
  compiled for one mesh must never rehydrate on another).
* Devices bind LATE (``bind``): the plan is built with abstract axis
  sizes (models/decode_engine.ShardingConfig) and the serving layer
  binds it to a concrete device slice — that is how the runtime
  places two tp=2 models on devices [0,1] and [2,3] of the 8-device
  mesh (inference/runtime/placement.py).

State round-trip stability: the executor pins BOTH entry and result
shardings for every mutated persistable to the plan's placement, so
donated state flows through repeated steps with a byte-stable layout
and a prepared handle never re-specializes mid-traffic (the
zero-steady-state-compiles contract, extended to sharded programs).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

__all__ = ["ShardingPlan", "attach_plan", "plan_of",
           "program_sharding_token"]


class ShardingPlan:
    """Placement table + named mesh for one sharded program family.

    ``axes``: ordered (axis name, size) pairs — the mesh shape.
    ``placements``: {var name -> {tensor dim -> axis name}} for every
    persistable/feed that is NOT replicated (unlisted = replicated).

    Reference counterpart: reference
    framework/details/multi_devices_graph_pass.cc:40 decided
    per-place replication/collectives by rewriting the SSA graph; the
    plan is that decision as declarative metadata GSPMD executes.
    """

    def __init__(self, axes: Sequence[Tuple[str, int]],
                 placements: Dict[str, Dict[int, str]],
                 label: str = ""):
        self.axes = tuple((str(n), int(s)) for n, s in axes)
        self.placements = {
            str(name): {int(d): str(a) for d, a in dims.items()}
            for name, dims in placements.items()}
        self.label = label
        self._mesh = None           # bound jax.sharding.Mesh
        self._device_ids: Tuple[int, ...] = ()

    # --- identity -----------------------------------------------------
    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    def axis_size(self, name: str) -> int:
        for n, s in self.axes:
            if n == name:
                return s
        return 1

    def token(self) -> tuple:
        """Stable content identity for cache keys and fingerprints:
        mesh shape + every placement + (when bound) the flat device
        ids, the executor's ``_mesh_token`` discipline — two plans
        differing in any of these must never share an executable."""
        return ("sharded", self.axes,
                tuple(sorted((n, tuple(sorted(d.items())))
                             for n, d in self.placements.items())),
                self._device_ids)

    # --- device binding -----------------------------------------------
    @property
    def is_bound(self) -> bool:
        return self._mesh is not None

    @property
    def mesh(self):
        if self._mesh is None:
            raise RuntimeError(
                f"ShardingPlan({self.label or self.axes}) is not "
                f"bound to devices yet — call plan.bind(devices) "
                f"(the serving placement step) before executing")
        return self._mesh

    def bind(self, devices=None) -> "ShardingPlan":
        """Bind the plan to a concrete device slice. ``devices=None``
        means "the first ``n_devices`` of ``jax.devices()`` WHEN
        UNBOUND, else keep the current binding" — a later server over
        an already-placed bundle that does not name a slice must not
        silently migrate the model back to the default slice (and
        version-bump every program under a live server). Rebinding to
        an explicitly DIFFERENT slice is allowed (the token changes,
        so cached executables miss cleanly)."""
        import numpy as np

        import jax
        from jax.sharding import Mesh

        if devices is None:
            if self._mesh is not None:
                return self  # keep the existing binding
            devices = jax.devices()[:self.n_devices]
        devices = list(devices)
        if len(devices) != self.n_devices:
            raise ValueError(
                f"ShardingPlan needs {self.n_devices} devices for "
                f"mesh {self.axes}, got {len(devices)}")
        ids = tuple(int(d.id) for d in devices)
        if self._mesh is not None and ids == self._device_ids:
            return self
        shape = tuple(s for _, s in self.axes)
        names = tuple(n for n, _ in self.axes)
        self._mesh = Mesh(np.array(devices).reshape(shape), names)
        self._device_ids = ids
        return self

    # --- shardings ----------------------------------------------------
    def _pspec(self, name: str, shape=None):
        from jax.sharding import PartitionSpec as P

        dims = self.placements.get(name)
        if not dims:
            return P()
        rank = len(shape) if shape is not None else \
            (max(dims) + 1)
        entries = [None] * rank
        for d, a in dims.items():
            if d >= rank:
                return P()  # rank changed under us: replicate, safe
            if shape is not None and shape[d] is not None \
                    and shape[d] >= 0 and shape[d] % self.axis_size(a):
                # non-divisible dim (the sharding.safe_spec rule):
                # replicate rather than error at device_put
                return P()
            entries[d] = a
        return P(*entries)

    def sharding_for(self, name: str, shape=None):
        """NamedSharding for one var (replicated when unlisted or the
        placement does not divide the shape)."""
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._pspec(name, shape))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def place_state(self, scope, names=None, shapes=None) -> int:
        """device_put every initialized scope value in ``names``
        (default: every placement key present in the scope) onto the
        mesh per its placement — the one-time serving placement step.
        Returns the number of arrays placed. Host-written state that
        is re-set as numpy later still lands correctly: the jit
        boundary's in_shardings re-places it per call."""
        import numpy as np

        import jax

        placed = 0
        if names is None:
            names = list(self.placements)
        for name in names:
            val = scope._get(name)
            if val is None:
                continue
            shape = tuple(np.shape(val))
            sh = self.sharding_for(name, shape)
            scope._set(name, jax.device_put(val, sh))
            placed += 1
        return placed

    def __repr__(self):
        return (f"ShardingPlan({self.label or ''} axes={self.axes}, "
                f"{len(self.placements)} placements, "
                f"bound={self.is_bound})")


def attach_plan(program, plan: Optional[ShardingPlan]) -> None:
    """Attach (or clear) the execution plan on a Program; bumps the
    version so prepared handles / cached facts re-resolve."""
    program._sharding_plan = plan
    program._version = getattr(program, "_version", 0) + 1


def plan_of(program) -> Optional[ShardingPlan]:
    return getattr(program, "_sharding_plan", None)


def program_sharding_token(program) -> tuple:
    """The plan token for executor cache keys / disk digests; () for
    unsharded programs (the historical key shape, so existing cache
    entries stay valid)."""
    plan = plan_of(program)
    if plan is None:
        return ()
    return plan.token()
