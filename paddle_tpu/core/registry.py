"""Op registry: kernels, shape inference, grad-op makers.

TPU-native analogue of the reference's OpInfoMap / REGISTER_OPERATOR
machinery (reference: paddle/fluid/framework/op_registry.h:197-270,
op_info.h, grad_op_desc_maker.h). Differences driven by XLA:

* A "kernel" is a pure JAX-traceable function over the op's inputs; the
  Executor traces a whole Block of them into ONE XLA computation, so there
  is no per-device kernel dispatch key -- XLA picks the device code.
* Gradients: the reference hand-writes a grad op per op plus a
  GradOpDescMaker. Here every differentiable op gets its grad op derived
  automatically through jax.vjp of the forward kernel (rematerialized in
  the backward pass -- a win on TPU where FLOPs are cheaper than HBM).
  Ops whose fluid grad semantics differ (dropout's saved mask, sparse
  embedding grads) register custom grad makers/kernels.
* Shape inference (reference shape_inference.h / each op's InferShape) is
  generic: we jax.eval_shape the kernel at two different fake batch sizes;
  output dims that vary are batch-dims (-1). Ops can override.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .program import GRAD_SUFFIX, Block, Operator, grad_var_name
from .types import to_jnp_dtype


class OpInfo:
    def __init__(self, type: str, kernel: Callable,
                 infer_shape: Optional[Callable] = None,
                 grad_maker=None, differentiable: bool = True,
                 inplace: Optional[Dict[str, str]] = None,
                 stop_gradient_slots=(), needs_rng: bool = False,
                 host_effect: bool = False):
        self.type = type
        self.kernel = kernel
        self.infer_shape = infer_shape
        self.grad_maker = grad_maker
        self.differentiable = differentiable
        # output slot -> input slot it aliases (buffer donation hint,
        # analogue of the reference's inplace_op_inference.h)
        self.inplace = inplace or {}
        # input slots that never receive gradient (e.g. integer indices)
        self.stop_gradient_slots = tuple(stop_gradient_slots)
        self.needs_rng = needs_rng
        # True for kernels that bridge to the host (io_callback /
        # pure_callback / trace-time host state): they run per-step but
        # cannot be lowered into a lax.scan over steps — the multi-step
        # executor (Executor.run_steps) falls back to the per-step path
        # when a block contains one (with the op named in the reason)
        self.host_effect = host_effect


_REGISTRY: Dict[str, OpInfo] = {}

# op type -> sharding propagation rule for the analysis layer's
# sharding domain (analysis/absint.py). A rule is a PURE function
#     rule(op, spec_of, shape_of, mesh) -> (out_specs, events)
# over Program metadata: `spec_of(name)`/`shape_of(name)` resolve an
# input var's abstract ShardSpec / static shape, `out_specs` maps
# output var names to their propagated ShardSpec, and `events` lists
# the CollectiveEvents (psum/allgather/reshard/conflict) the op's
# GSPMD lowering implies under those specs. Rules live alongside the
# kernels they describe (analysis/sharding_rules.py registers the
# core families) so a new op that touches sharded state registers its
# propagation fact the same way it registers its kernel — an op
# WITHOUT a rule degrades its outputs to the explicit ⊤ spec
# (warn-once) the moment a sharded value reaches it, so imprecision
# is visible, never silently wrong.
_SHARDING_RULES: Dict[str, Callable] = {}


def register_sharding_rule(op_types, fn: Optional[Callable] = None):
    """Register a sharding-propagation rule for one op type or a
    family of op types (mirrors register_op; usable as a decorator).

    Reference counterpart: none — the reference shards at runtime via
    transpilers (reference transpiler/distribute_transpiler.py), so a
    compile-time per-op sharding algebra had nothing to attach to.
    """
    if isinstance(op_types, str):
        op_types = (op_types,)

    def deco(f):
        for t in op_types:
            _SHARDING_RULES[t] = f
        return f

    return deco(fn) if fn is not None else deco


def get_sharding_rule(op_type: str) -> Optional[Callable]:
    return _SHARDING_RULES.get(op_type)


def has_sharding_rule(op_type: str) -> bool:
    return op_type in _SHARDING_RULES


def sharding_rule_types() -> List[str]:
    return sorted(_SHARDING_RULES)


# op type -> pool-index PROVENANCE rule for the analysis layer's
# ownership domain (analysis/absint.py). A rule is a PURE function
#     rule(op, prov_of, shape_of) -> {out_name: ProvFact}
# over Program metadata: it states how the op carries symbolic
# provenance of pool indices (host-owned table tags, trace-time
# constants, 0/1 indicators, value bounds) from inputs to outputs.
# Families live in analysis/ownership_rules.py, beside the sharding
# families; an op WITHOUT a rule propagates NO provenance, so an
# index that flows through it reaches a @POOL access with UNKNOWN
# provenance and PTA190 rejects the access — imprecision is a loud
# error at the one place it matters, never a silent pass.
_INDEX_RULES: Dict[str, Callable] = {}


def register_index_rule(op_types, fn: Optional[Callable] = None):
    """Register a pool-index provenance rule for one op type or a
    family (mirrors register_sharding_rule; usable as a decorator).

    Reference counterpart: none — the reference checks allocator
    state at RUNTIME (reference framework/scope.cc Var lookups); a
    compile-time index-provenance algebra is the shared-pool-era
    capability the whole-block-jit serving path needs instead.
    """
    if isinstance(op_types, str):
        op_types = (op_types,)

    def deco(f):
        for t in op_types:
            _INDEX_RULES[t] = f
        return f

    return deco(fn) if fn is not None else deco


def get_index_rule(op_type: str) -> Optional[Callable]:
    return _INDEX_RULES.get(op_type)


def has_index_rule(op_type: str) -> bool:
    return op_type in _INDEX_RULES


def index_rule_types() -> List[str]:
    return sorted(_INDEX_RULES)


# ownership tag -> acquire/release CONTRACT for the analysis layer's
# liveness domain (analysis/liveness.py). Where the index rules above
# prove WHERE a pool index came from, a contract declares the
# obligation that acquiring through that tag creates — which host
# call mints the hold, which call discharges it, and the exhaustive
# set of protocol exit paths on which the discharge must be proven to
# run (normal retirement, preemption, abort, invalidate, session
# close, server close, future cancel). PTA201 walks these: a tag a
# program actually exercises with NO contract, or a declared exit
# path with NO registered release site, is an unproven obligation —
# an error, never a silent pass. Contracts register via
# absint.register_acquire_release (which validates the tag against
# the ownership-source table); release SITES register from the code
# that implements them (inference/serving.py) so the ledger names
# real methods, not prose.
_ACQUIRE_CONTRACTS: Dict[str, object] = {}

# (tag, exit_path) -> list of "module.method" site strings proving
# the release runs on that path.
_RELEASE_SITES: Dict[Tuple[str, str], List[str]] = {}


def register_acquire_contract(tag: str, contract: object) -> None:
    """Register the acquire/release contract for an ownership tag.
    Idempotent on identical re-registration; raises on a DIFFERING
    redefinition (two subsystems disagreeing about an obligation is
    a bug, not a merge).

    Reference counterpart: none — the reference frees at runtime via
    GC passes (reference framework/executor_gc.md); a static
    obligation registry is the proof-tier analogue.
    """
    prev = _ACQUIRE_CONTRACTS.get(tag)
    if prev is not None:
        if prev == contract:
            return
        raise ValueError(
            f"acquire contract for {tag!r} already registered with "
            f"different terms: {prev} vs {contract}")
    _ACQUIRE_CONTRACTS[tag] = contract


def get_acquire_contract(tag: str):
    return _ACQUIRE_CONTRACTS.get(tag)


def acquire_contracts() -> Dict[str, object]:
    return dict(_ACQUIRE_CONTRACTS)


def register_release_site(tag: str, exit_path: str,
                          site: str) -> None:
    """Record that `site` (a "Class.method" string in the serving
    layer) discharges `tag`'s obligation on `exit_path`. Append-only
    and idempotent per site. Validation that the tag has a contract
    and declares the exit lives in absint.register_release_site (the
    public wrapper) — this is the bare store.

    Reference counterpart: none (see register_acquire_contract).
    """
    sites = _RELEASE_SITES.setdefault((tag, exit_path), [])
    if site not in sites:
        sites.append(site)


def release_sites() -> Dict[Tuple[str, str], List[str]]:
    return {k: list(v) for k, v in _RELEASE_SITES.items()}


def kernel_bridges_host(fn: Callable) -> bool:
    """True when `fn`'s code references jax's io_callback/pure_callback
    host bridges — directly, in nested functions, or through helper
    functions defined in the SAME module (a kernel that factors its
    callback into a shared module helper must still trip the
    host_effect assert). Works off code objects (co_names covers both
    module-level imports and function-local `from jax.experimental
    import io_callback`), so it costs microseconds at registration —
    no source parsing. Cross-module helpers are not followed; a
    kernel delegating its host bridge to another module must carry
    host_effect=True explicitly."""
    import types

    targets = ("io_callback", "pure_callback")
    seen = set()

    def scan_fn(f):
        code = getattr(f, "__code__", None)
        if code is None or id(code) in seen:
            return False  # seen: also breaks mutual-recursion cycles
        if scan_code(code):
            return True
        # follow same-module helper functions referenced by name
        module = getattr(f, "__module__", None)
        globs = getattr(f, "__globals__", {})
        for name in code.co_names:
            g = globs.get(name)
            if isinstance(g, types.FunctionType) and \
                    g.__module__ == module and scan_fn(g):
                return True
        return False

    def scan_code(code):
        if id(code) in seen:
            return False
        seen.add(id(code))
        if any(n in code.co_names for n in targets):
            return True
        return any(isinstance(c, types.CodeType) and scan_code(c)
                   for c in code.co_consts)

    return scan_fn(fn)

# placeholder input name meaning "no value" (e.g. an output grad that is
# never reached by backprop); run_op resolves it to None and the vjp grad
# kernel substitutes zeros (reference uses fill_zeros_like ops instead).
EMPTY_VAR = "@EMPTY@"


def get_op_info(type: str) -> OpInfo:
    if type not in _REGISTRY:
        raise KeyError(f"Operator {type!r} is not registered "
                       f"({len(_REGISTRY)} ops registered)")
    return _REGISTRY[type]


def is_registered(type: str) -> bool:
    return type in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


class OpContext:
    """What a kernel sees: resolved input values + attrs + a PRNG tap."""

    __slots__ = ("op", "attrs", "_inputs", "_rng_cell", "_rng_salt",
                 "_rng_calls")

    def __init__(self, op: Operator, inputs: Dict[str, List],
                 rng_cell=None, rng_salt: int = 0):
        self.op = op
        self.attrs = op.attrs
        self._inputs = inputs
        self._rng_cell = rng_cell  # single-element list holding step key
        self._rng_salt = rng_salt
        self._rng_calls = 0

    def input(self, slot, idx=0):
        vals = self._inputs.get(slot)
        if not vals:
            return None
        return vals[idx]

    def inputs(self, slot) -> List:
        return list(self._inputs.get(slot, []))

    def has_input(self, slot):
        return bool(self._inputs.get(slot))

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def rng(self):
        """Derive this op's PRNG key from the per-step key.

        Purely functional: key = fold_in(step_key, op uid) -- never
        advances shared state, so the vjp grad kernel can reproduce the
        exact forward noise by re-deriving with the same salt. The
        executor advances the step key once per step instead."""
        if self._rng_cell is None:
            # shape-inference / eval_shape path: abstract key is fine
            return jax.random.PRNGKey(0)
        key = jax.random.fold_in(self._rng_cell[0], self._rng_salt)
        if self._rng_calls:
            key = jax.random.fold_in(key, self._rng_calls)
        self._rng_calls += 1
        return key


def register_op(type: str, *, infer_shape=None, grad_maker=None,
                differentiable=True, inplace=None, stop_gradient_slots=(),
                needs_rng=False, host_effect=False, sharding_rule=None):
    """Decorator: register `fn(ctx) -> {out_slot: value|[values]}`.
    `sharding_rule` optionally registers the op's sharding-propagation
    rule in the same breath (see register_sharding_rule)."""

    def deco(fn):
        if sharding_rule is not None:
            register_sharding_rule(type, sharding_rule)
        if not host_effect and kernel_bridges_host(fn):
            # the r6 'REMEMBER the flag' learning, mechanized: a
            # host-bridging kernel registered without the flag would be
            # silently lowered into Executor.run_steps' device-resident
            # lax.scan, breaking its once-per-step host semantics
            raise RuntimeError(
                f"op {type!r}: kernel references io_callback/"
                f"pure_callback but is registered with "
                f"host_effect=False — register with host_effect=True "
                f"so Executor.run_steps falls back to the per-step "
                f"path (analysis checker PTA070)")
        _REGISTRY[type] = OpInfo(
            type, fn, infer_shape=infer_shape, grad_maker=grad_maker,
            differentiable=differentiable, inplace=inplace,
            stop_gradient_slots=stop_gradient_slots, needs_rng=needs_rng,
            host_effect=host_effect)
        return fn

    return deco


def _normalize_outputs(op: Operator, raw) -> Dict[str, List]:
    out: Dict[str, List] = {}
    if raw is None:
        return out
    if not isinstance(raw, dict):
        # single-output convenience: bind to the op's single output slot
        slots = [s for s in op.outputs if op.outputs[s]]
        if len(slots) != 1:
            raise ValueError(
                f"op {op.type} returned a bare value but has output slots "
                f"{list(op.outputs)}")
        raw = {slots[0]: raw}
    for slot, vals in raw.items():
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        out[slot] = list(vals)
    return out


def run_op(op: Operator, env: Dict, rng_cell=None, rng_salt=0) -> None:
    """Execute one op against an env of name->traced value; write outputs."""
    info = get_op_info(op.type)
    inputs: Dict[str, List] = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n == EMPTY_VAR:
                vals.append(None)
            elif n not in env:
                raise KeyError(
                    f"op {op.type}: input var {n!r} (slot {slot}) not "
                    f"materialized; known={sorted(list(env))[:20]}...")
            else:
                vals.append(env[n])
        inputs[slot] = vals
    from .. import amp

    if amp.enabled():
        inputs = amp.cast_op_inputs(op.type, inputs)
    ctx = OpContext(op, inputs, rng_cell=rng_cell, rng_salt=rng_salt)
    raw = info.kernel(ctx)
    outs = _normalize_outputs(op, raw)
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if len(vals) != len(names):
            raise ValueError(
                f"op {op.type}: slot {slot} produced {len(vals)} values for "
                f"{len(names)} output vars")
        for n, v in zip(names, vals):
            env[n] = v


# ---------------------------------------------------------------------------
# Generic shape inference: eval_shape at two fake batch sizes; dims that
# move with the fake size are dynamic (-1).
# ---------------------------------------------------------------------------
_PROBE_A, _PROBE_B = 7, 11
_INFER_WARNED: set = set()


def _probe_spec(var, probe):
    shape = tuple(probe if d == -1 else d for d in (var.shape or ()))
    dtype = to_jnp_dtype(var.dtype or "float32")
    return jax.ShapeDtypeStruct(shape, dtype)


def _snapshot_output_decls(op: Operator, block: Block):
    """Pre-inference (var, shape, dtype) of the op's existing output
    vars — the evidence base for the PTA140 declared-shape-clobber
    checker (analysis/checkers.py). Output names with NO var yet are
    recorded as (name, None): the var the inference pass creates for
    them is inference-shaped from birth, never a declaration."""
    snap = []
    missing = []
    for n in op.output_arg_names:
        v = block._find_var_recursive(n)
        if v is not None:
            snap.append((v, v.shape, v.dtype))
        else:
            missing.append(n)
    return snap, missing


def _record_decl_clobbers(snap) -> None:
    """Build-time shape inference OVERWRITES a var's declared
    shape/dtype with the producer's inferred one, in place (the r10
    incident: assign of a [-1,4] value onto a concretely-declared
    persistable rewrites it to [-1,4], silently breaking the var's
    feed/carry contract). The declaration is unrecoverable after the
    fact, so this hook stashes it on FIRST clobber: a shape/dtype that
    was present before any inference pass changed it is the builder's
    declaration (`_declared_shape`/`_declared_dtype`); shapes a prior
    inference pass itself wrote (`_shape_inferred`) are producer
    facts, not declarations — multi-writer temps never false-positive.
    The PTA140 checker reads the stash."""
    for v, shape0, dtype0 in snap:
        if v.shape != shape0:
            if shape0 is not None and \
                    not getattr(v, "_shape_inferred", False) and \
                    not hasattr(v, "_declared_shape"):
                v._declared_shape = tuple(shape0)
            v._shape_inferred = True
        if v.dtype != dtype0:
            if dtype0 is not None and \
                    not getattr(v, "_dtype_inferred", False) and \
                    not hasattr(v, "_declared_dtype"):
                v._declared_dtype = dtype0
            v._dtype_inferred = True


def infer_shape_for_op(op: Operator, block: Block) -> None:
    info = _REGISTRY.get(op.type)
    if info is None:
        return  # unregistered (e.g. feed/fetch placeholders) -- skip
    snap, missing = _snapshot_output_decls(op, block)
    try:
        _infer_shape_for_op(op, block, info)
    finally:
        _record_decl_clobbers(snap)
        for n in missing:
            v = block._find_var_recursive(n)
            if v is not None:
                # created by this inference pass: its metadata is a
                # producer fact from birth, never a declaration
                v._shape_inferred = True
                v._dtype_inferred = True


def _infer_shape_for_op(op: Operator, block: Block, info) -> None:
    if info.infer_shape is not None:
        info.infer_shape(op, block)
        return
    try:
        results = []
        for probe in (_PROBE_A, _PROBE_B):
            ins = {}
            ok = True
            for slot, names in op.inputs.items():
                vals = []
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is None or v.shape is None or v.dtype is None:
                        ok = False
                        break
                    vals.append(_probe_spec(v, probe))
                if not ok:
                    break
                ins[slot] = vals
            if not ok:
                return

            def f(ins):
                ctx = OpContext(op, ins)
                return _normalize_outputs(op, info.kernel(ctx))

            results.append(jax.eval_shape(f, ins))
    except Exception as e:
        # Reference InferShape raises at build time (framework/
        # shape_inference.h). Here kernels double as shape functions via
        # eval_shape, and some legitimately cannot trace with -1 probe
        # dims -- so default is warn-and-defer, with FLAGS_strict_infer_
        # shape=1 restoring raise-at-append_op semantics.
        from ..flags import FLAGS

        if FLAGS.strict_infer_shape:
            raise RuntimeError(
                f"shape inference failed for op {op.type!r}: {e}") from e
        if op.type not in _INFER_WARNED:
            _INFER_WARNED.add(op.type)
            import warnings

            warnings.warn(
                f"shape inference for op {op.type!r} failed at build "
                f"time ({type(e).__name__}: {e}); output shapes left "
                f"unset -- errors may surface later at trace time. Set "
                f"FLAGS_strict_infer_shape=1 to raise here instead.")
        return
    ra, rb = results
    for slot, names in op.outputs.items():
        if slot not in ra:
            continue
        for n, sa, sb in zip(names, ra[slot], rb[slot]):
            var = block._find_var_recursive(n)
            if var is None:
                var = block.create_var(name=n)
            shape = tuple(
                da if da == db else -1
                for da, db in zip(sa.shape, sb.shape))
            var.shape = shape
            from .types import as_datatype

            var.dtype = as_datatype(sa.dtype.name)


# ---------------------------------------------------------------------------
# Generic grad machinery: <type>_grad op derived via jax.vjp of the forward.
# ---------------------------------------------------------------------------
def _is_float_dtype(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)


def make_vjp_grad_kernel(fwd_type: str):
    """Build the kernel for `<fwd_type>_grad`.

    The grad op's inputs are the forward inputs plus `<slot>@GRAD` entries
    for each forward output slot; outputs are `<slot>@GRAD` for each
    differentiable forward input slot. The forward is recomputed inside the
    vjp (rematerialization) -- on TPU this trades cheap MXU FLOPs for HBM.
    """
    def kernel(ctx: OpContext):
        info = get_op_info(fwd_type)
        fwd_op = ctx.attr("__fwd_op__")
        # partition ctx inputs into forward inputs vs output cotangents
        fwd_inputs = {s: ctx.inputs(s) for s in fwd_op.inputs}
        # flatten differentiable leaves
        diff_paths, diff_leaves, const = [], [], {}
        for slot, vals in fwd_inputs.items():
            keep = (slot not in info.stop_gradient_slots)
            for i, v in enumerate(vals):
                if keep and _is_float_dtype(v):
                    diff_paths.append((slot, i))
                    diff_leaves.append(v)
                else:
                    const[(slot, i)] = v

        def f(leaves):
            ins = {s: [None] * len(v) for s, v in fwd_inputs.items()}
            for (s, i), v in const.items():
                ins[s][i] = v
            for (s, i), v in zip(diff_paths, leaves):
                ins[s][i] = v
            # same step key + the FORWARD op's salt: the recomputed
            # forward draws the identical noise the real forward drew
            inner = OpContext(fwd_op, ins, rng_cell=ctx._rng_cell,
                              rng_salt=fwd_op._uid)
            return _normalize_outputs(fwd_op, info.kernel(inner))

        outs, vjp_fn = jax.vjp(f, diff_leaves)
        # assemble cotangents in the same structure as outs
        cots = {}
        for slot, vals in outs.items():
            gs = ctx.inputs(slot + GRAD_SUFFIX)
            slot_cots = []
            for i, v in enumerate(vals):
                if gs and i < len(gs) and gs[i] is not None:
                    g = gs[i]
                    if g.dtype != v.dtype:
                        g = g.astype(v.dtype)
                    slot_cots.append(g)
                else:
                    slot_cots.append(jnp.zeros_like(v))
            cots[slot] = slot_cots
        (grads,) = vjp_fn(cots)
        result: Dict[str, List] = {}
        for (slot, i), g in zip(diff_paths, grads):
            names = fwd_op.inputs[slot]
            result.setdefault(slot + GRAD_SUFFIX,
                              [None] * len(names))[i] = g
        # drop slots whose grads were all skipped
        return {s: v for s, v in result.items()
                if any(x is not None for x in v)}

    return kernel


def default_grad_maker(op: Operator, no_grad_set=frozenset()):
    """Create the grad OpDesc for `op` (reference grad_op_desc_maker.h).

    Returns a list of Operator descs (not yet appended to any block).
    """
    info = get_op_info(op.type)
    if not info.differentiable:
        return []
    grad_type = op.type + "_grad"
    if not is_registered(grad_type):
        register_op(grad_type, differentiable=False)(
            make_vjp_grad_kernel(op.type))
    inputs = {s: list(v) for s, v in op.inputs.items()}
    for slot, names in op.outputs.items():
        inputs[slot + GRAD_SUFFIX] = [grad_var_name(n) for n in names]
    outputs = {}
    for slot, names in op.inputs.items():
        if slot in info.stop_gradient_slots:
            continue
        grads = [grad_var_name(n) for n in names]
        if all(g in no_grad_set or n in no_grad_set
               for g, n in zip(grads, names)):
            continue
        outputs[slot + GRAD_SUFFIX] = grads
    if not outputs:
        return []
    attrs = dict(op.attrs)
    attrs["__fwd_op__"] = op
    return [Operator(op.block, grad_type, inputs, outputs, attrs)]


def make_grad_ops(op: Operator, no_grad_set=frozenset()):
    info = get_op_info(op.type)
    if info.grad_maker is not None:
        return info.grad_maker(op, no_grad_set)
    return default_grad_maker(op, no_grad_set)
