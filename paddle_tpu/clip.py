"""Gradient clipping (reference python/paddle/fluid/clip.py:
ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
GradientClipByGlobalNorm, set_gradient_clip).
"""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = ["ErrorClipByValue", "GradientClipByValue",
           "GradientClipByNorm", "GradientClipByGlobalNorm",
           "set_gradient_clip", "append_gradient_clip_ops",
           "error_clip_callback"]


class BaseErrorClipAttr:
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _append_clip_op(self, block, grad_name):
        block.append_op("clip", {"X": grad_name}, {"Out": grad_name},
                        {"min": self.min, "max": self.max,
                         "op_role": "backward"})


def error_clip_callback(block, context):
    pass


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(name=grad.name + "@CLIP",
                               shape=grad.shape, dtype=grad.dtype)
        block.append_op("clip", {"X": grad}, {"Out": out},
                        {"min": self.min, "max": self.max,
                         "op_role": "backward"})
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(name=grad.name + "@CLIP",
                               shape=grad.shape, dtype=grad.dtype)
        block.append_op("clip_by_norm", {"X": grad}, {"Out": out},
                        {"max_norm": self.clip_norm,
                         "op_role": "backward"})
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        ctx = context.setdefault(self.group_name,
                                 {"grads": [], "clip_norm":
                                  self.clip_norm})
        ctx["grads"].append((param, grad))

    def _create_operators(self, param, grad):
        return param, grad  # handled at group level


def _apply_global_norm_group(group):
    from . import layers

    grads = group["grads"]
    clip_norm = group["clip_norm"]
    sq_sums = []
    for _, g in grads:
        block = g.block
        sq = block.create_var(name=g.name + "@SQSUM", shape=(1,),
                              dtype=g.dtype)
        block.append_op("squared_l2_norm", {"X": g}, {"Out": sq},
                        {"op_role": "backward"})
        sq_sums.append(sq)
    block = grads[0][1].block
    total = block.create_var(name=grads[0][1].name + "@GLOBALSQ",
                             shape=(1,), dtype=grads[0][1].dtype)
    block.append_op("sum", {"X": sq_sums}, {"Out": total},
                    {"op_role": "backward"})
    gnorm = block.create_var(name=grads[0][1].name + "@GNORM",
                             shape=(1,), dtype=grads[0][1].dtype)
    block.append_op("sqrt", {"X": total}, {"Out": gnorm},
                    {"op_role": "backward"})
    # scale = clip_norm / max(gnorm, clip_norm)
    denom = block.create_var(name=gnorm.name + "@MAX", shape=(1,),
                             dtype=gnorm.dtype)
    cn_var = block.create_var(name=gnorm.name + "@CN", shape=(1,),
                              dtype=gnorm.dtype)
    block.append_op("fill_constant", {}, {"Out": cn_var},
                    {"shape": [1], "dtype": "float32",
                     "value": float(clip_norm), "op_role": "backward"})
    block.append_op("elementwise_max", {"X": gnorm, "Y": cn_var},
                    {"Out": denom}, {"op_role": "backward"})
    scale_var = block.create_var(name=gnorm.name + "@SCALE", shape=(1,),
                                 dtype=gnorm.dtype)
    block.append_op("elementwise_div", {"X": cn_var, "Y": denom},
                    {"Out": scale_var}, {"op_role": "backward"})
    result = []
    for p, g in grads:
        out = g.block.create_var(name=g.name + "@GCLIP",
                                 shape=g.shape, dtype=g.dtype)
        g.block.append_op("elementwise_mul",
                          {"X": g, "Y": scale_var}, {"Out": out},
                          {"axis": -1, "op_role": "backward"})
        result.append((p, out))
    return result


_clip_attr = {}


def set_gradient_clip(clip, param_list=None, program=None):
    from .core.program import default_main_program

    program = program or default_main_program()
    if param_list is None:
        param_list = program.all_parameters()
    for p in param_list:
        name = p if isinstance(p, str) else p.name
        _clip_attr[name] = clip


def append_gradient_clip_ops(param_grads):
    context = {}
    result = []
    global_groups = []
    for p, g in param_grads:
        if g is None:
            result.append((p, g))
            continue
        clip = _clip_attr.get(p.name) or getattr(p, "error_clip", None)
        if clip is None:
            result.append((p, g))
            continue
        if isinstance(clip, GradientClipByGlobalNorm):
            clip._process_context(context, p, g)
            global_groups.append((p.name, clip.group_name))
        else:
            result.append(clip._create_operators(p, g))
    for group_name, group in context.items():
        result.extend(_apply_global_norm_group(group))
    return result
