"""Generic dataflow engine over the Program IR.

The reference validates programs in C++ before execution
(reference paddle/fluid/framework/op_desc.cc CheckAttrs/InferShape,
operator.cc:975 RunImpl enforcement); the TPU-native Executor compiles
a whole Block in one shot, so there is no per-op hook to catch a
malformed program — it surfaces as a jax trace error, a wrong number,
or a wedged TPU tunnel. This module computes the structural facts the
checker suite (analysis/checkers.py) reads: def-use chains per block,
recursive sub-block walking (the same Block-attr walk
core/executor.py's _scan_fallback_reason does), and writer/reader
indices with stable op anchors.

Everything here is pure Python over Program/Block/Operator metadata —
no jax, no tracing: a whole model program analyzes in milliseconds.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from ..core.program import Block, Operator, Program
from ..core.registry import EMPTY_VAR

__all__ = ["BlockDataflow", "analyze_block", "iter_sub_blocks",
           "iter_blocks", "iter_ops", "OpSite", "block_entry_names",
           "register_block_entry_attrs", "BLOCK_ENTRY_ATTRS"]


@dataclass(frozen=True)
class OpSite:
    """Stable anchor for one op occurrence: (block idx, op position).

    `container` is the op whose Block-typed attr holds this op's block
    (None for ops sitting in a block reached straight from the program
    block list walk), letting checkers distinguish "inside a while
    body" from "top level".
    """
    block_idx: int
    op_idx: int
    op: Operator
    container: Optional[Operator] = None

    def anchor(self) -> str:
        where = f"block {self.block_idx} op {self.op_idx}"
        if self.container is not None:
            where += f" (inside {self.container.type!r})"
        return f"{self.op.type} @ {where}"


@dataclass
class BlockDataflow:
    """Def-use facts for ONE block (sub-blocks are separate analyses).

    writers/readers map var name -> op positions in block order;
    `first_write`/`first_read` are the minimum positions. Names on the
    op's input slots count as reads, output slots as writes; EMPTY_VAR
    placeholders are ignored.
    """
    block: Block
    writers: Dict[str, List[int]] = field(default_factory=dict)
    readers: Dict[str, List[int]] = field(default_factory=dict)

    @property
    def first_write(self) -> Dict[str, int]:
        return {n: idxs[0] for n, idxs in self.writers.items()}

    @property
    def first_read(self) -> Dict[str, int]:
        return {n: idxs[0] for n, idxs in self.readers.items()}

    def multi_writers(self) -> Dict[str, List[int]]:
        return {n: idxs for n, idxs in self.writers.items()
                if len(idxs) > 1}


def analyze_block(block: Block) -> BlockDataflow:
    df = BlockDataflow(block)
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            if n == EMPTY_VAR:
                continue
            df.readers.setdefault(n, []).append(i)
        for n in op.output_arg_names:
            if n == EMPTY_VAR:
                continue
            df.writers.setdefault(n, []).append(i)
    return df


def iter_sub_blocks(op: Operator) -> Iterator[Tuple[str, Block]]:
    """Block-typed attrs of one op (sub_block / true_block / ...)."""
    for k, v in op.attrs.items():
        if isinstance(v, Block):
            yield k, v


def iter_blocks(program: Program) -> Iterator[Tuple[Block,
                                                    Optional[Operator]]]:
    """Every block reachable from the program, with the op that
    contains it (None for blocks no control-flow op references — the
    global block, and orphaned builds). Blocks live both in
    program.blocks and behind op attrs; the attr walk establishes the
    container relation, the list walk catches strays. Each block is
    yielded once."""
    containers: Dict[int, Operator] = {}
    seen = set()
    stack: List[Block] = [program.global_block]
    while stack:
        blk = stack.pop()
        if id(blk) in seen:
            continue
        seen.add(id(blk))
        yield blk, containers.get(id(blk))
        for op in blk.ops:
            for _, sub in iter_sub_blocks(op):
                containers.setdefault(id(sub), op)
                stack.append(sub)
    for blk in program.blocks:
        if id(blk) not in seen:
            seen.add(id(blk))
            yield blk, containers.get(id(blk))


def iter_ops(program: Program) -> Iterator[OpSite]:
    """Every op in every reachable block, as anchored OpSites."""
    for blk, container in iter_blocks(program):
        for i, op in enumerate(blk.ops):
            yield OpSite(blk.idx, i, op, container)


# op type -> the attr names whose string lists genuinely SEED the
# sub-block environment (read straight from the kernels:
# ops/control_flow_ops.py builds while/run_block_if envs from
# externals+carried and conditional_block's from its X inputs only;
# ops/lod_ops.py builds ifelse branch envs from externals and
# recurrent step envs from externals + per-step x_names + pre_names).
# Output-name lists (true_out/false_out, out_names, mem_names) are
# PRODUCED inside the block — treating them as entries (the old
# any-all-str-list heuristic) over-seeded PTA001 and masked true
# uninitialized reads.
BLOCK_ENTRY_ATTRS: Dict[str, Tuple[str, ...]] = {
    "while": ("carried", "externals"),
    "run_block_if": ("carried", "externals"),
    "conditional_block": (),
    "ifelse": ("externals",),
    "recurrent": ("externals", "x_names", "pre_names"),
    "go": (),
}

_ENTRY_FALLBACK_WARNED: set = set()


def register_block_entry_attrs(op_type: str,
                               attr_names: Tuple[str, ...]) -> None:
    """Register which of a NEW container op's list attrs seed its
    sub-block environment (mirrors core/registry.register_op: a
    sub-block-carrying op added without an entry registration falls
    back to the permissive heuristic with a warn-once, so the gap is
    visible instead of silent)."""
    BLOCK_ENTRY_ATTRS[op_type] = tuple(attr_names)


def block_entry_names(op: Operator) -> set:
    """Names a control-flow op's sub-block environment starts with.

    The sub-block kernels build a FRESH env: parent-block vars are NOT
    visible unless declared through the op's inputs or the registered
    entry-name attrs (BLOCK_ENTRY_ATTRS). This is the seed set an
    uninitialized-read analysis of the sub-block must start from.

    Unregistered container op types fall back to the old permissive
    heuristic — every all-str list attr counts — with a warn-once:
    over-seeding can MASK true uninitialized reads (PTA001), so the
    fallback is a visible stopgap, not the contract."""
    names = set(op.input_arg_names)
    registered = BLOCK_ENTRY_ATTRS.get(op.type)
    if registered is not None:
        for attr in registered:
            v = op.attrs.get(attr)
            if isinstance(v, (list, tuple)):
                names.update(x for x in v if isinstance(x, str))
        return names
    if op.type not in _ENTRY_FALLBACK_WARNED:
        _ENTRY_FALLBACK_WARNED.add(op.type)
        import warnings

        warnings.warn(
            f"block_entry_names: container op type {op.type!r} has no "
            f"registered entry-name attrs; falling back to the "
            f"permissive any-all-str-list heuristic, which can mask "
            f"uninitialized-read findings (PTA001). Register it via "
            f"analysis.dataflow.register_block_entry_attrs.")
    for v in op.attrs.values():
        if isinstance(v, (list, tuple)) and v and all(
                isinstance(x, str) for x in v):
            names.update(v)
    return names
