"""CI lint baseline: snapshot the zoo's diagnostic set, fail on drift.

Reference counterpart: the reference gates programs one at a time at
build (op_desc.cc CheckAttrs); a repo-wide DIAGNOSTIC SET gate has no
reference analogue — it is the compile-time equivalent of a golden
test. The strict CLI already fails on errors; once warnings matter
(the divergence prover emits proof-carrying warnings whose regression
is a real signal), "no new error-OR-warning anywhere in the 73-program
zoo" needs a committed snapshot to diff against. That snapshot is
``analysis_baseline.json`` at the repo root:

* ``python -m paddle_tpu.analysis --write-baseline`` regenerates it
  (review the diff like any golden change);
* ``python -m paddle_tpu.analysis --baseline`` (CI, and the tier-1
  gate test tests/test_analysis_gate.py in-process) exits 2 when any
  NEW error-or-warning appears vs the snapshot — resolved findings
  only print a refresh reminder, so fixes never fail the gate.

Baseline keys are ``target|code|severity|op_type|var`` with counts —
stable under op-index drift (message positions move; the finding
class does not). Suppressed diagnostics (`_pta_suppress`) are
recorded under their own section: suppressing is reviewable debt the
baseline makes visible, not a disappearance.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .checkers import (Diagnostic, ERROR, INFO, WARNING, check_bundle,
                       check_cross_model_collision, check_shared_params,
                       run_checks)

__all__ = ["TargetReport", "collect_reports", "baseline_payload",
           "diff_against_baseline", "write_baseline", "load_baseline",
           "default_baseline_path", "BASELINE_FILENAME"]

BASELINE_FILENAME = "analysis_baseline.json"

_PAIR_CHECKERS = {"shared_params": check_shared_params,
                  "cross_model": check_cross_model_collision}


@dataclass
class TargetReport:
    """Diagnostics for ONE linted program (or bundle) of the zoo."""
    target: str
    diagnostics: List[Diagnostic] = field(default_factory=list)
    suppressed: List[Tuple[Diagnostic, str]] = field(
        default_factory=list)
    # stable propagated-sharding snapshot (absint
    # stable_sharding_facts): var -> spec description; feeds the
    # baseline's drift-gated `sharding_facts` section
    sharding: Dict[str, str] = field(default_factory=dict)
    # stable pool-ownership snapshot (absint stable_ownership_facts):
    # pool var -> proven access summary (+ the '@assumptions' roll-up
    # of named host-allocator invariants the proofs rest on); feeds
    # the baseline's drift-gated `ownership_facts` section
    ownership: Dict[str, str] = field(default_factory=dict)
    # the per-target assumptions/obligations ledger (absint
    # ownership_ledger): the CLI --json surface, never baselined raw
    # (site counts churn with op-count tweaks; the FACTS above gate)
    ownership_ledger: dict = field(default_factory=dict)
    # stable liveness snapshot (liveness.stable_liveness_facts for
    # programs, liveness.bundle_liveness_facts for bundles): While
    # variant verdicts, the release-obligation roll-up, and
    # admission-capacity feasibility; feeds the baseline's
    # drift-gated `liveness_facts` section
    liveness: Dict[str, str] = field(default_factory=dict)
    # the per-target release-obligation ledger
    # (liveness.obligation_ledger): the CLI --json surface, never
    # baselined raw (site lists churn; the FACTS above gate)
    liveness_ledger: dict = field(default_factory=dict)
    # static per-device memory plan (analysis/memplan.MemoryPlan);
    # filled only when collect_reports(with_plans=True) — the CLI's
    # --memory-plan surface
    plan: object = None

    def by_severity(self, severity: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]


def collect_reports(include_benchmark: bool = True,
                    only: Optional[List[str]] = None,
                    targets=None,
                    collect_timings: Optional[Dict[str, float]] = None,
                    with_plans: bool = False) -> List[TargetReport]:
    """Build (or accept pre-built) lint targets and run the FULL
    sweep over each: per-program checkers (with suppressions
    collected), the target's pairwise check, and the whole-bundle
    contract check for every bundle the target ships. One code path
    shared by the CLI and the tier-1 gate test — the gate must test
    the sweep CI actually runs.

    Reference counterpart: none — the reference gated one program at
    a time at build (op_desc.cc); a repo-wide diagnostic sweep is the
    CI-era extension (module docstring)."""
    from . import absint, liveness
    from .targets import iter_lint_targets

    if targets is None:
        targets = iter_lint_targets(
            include_benchmark=include_benchmark, only=only)
    reports: List[TargetReport] = []
    for target in targets:
        pair_check = _PAIR_CHECKERS[target.pair_check]
        for label, prog in target.programs.items():
            rep = TargetReport(f"{target.name}:{label}")
            rep.diagnostics = run_checks(
                prog, collect_suppressed=rep.suppressed,
                collect_timings=collect_timings)
            facts = absint.analyze(prog)
            rep.sharding = facts.stable_sharding_facts()
            rep.ownership = facts.stable_ownership_facts()
            rep.ownership_ledger = facts.ownership_ledger()
            rep.liveness = liveness.stable_liveness_facts(facts)
            rep.liveness_ledger = liveness.obligation_ledger(facts)
            if with_plans:
                try:
                    rep.plan = facts.device_memory_plan()
                except Exception:
                    rep.plan = None  # planner must never kill lint
            for a, b in target.pairs:
                if label == a:
                    rep.diagnostics = rep.diagnostics + pair_check(
                        target.programs[a], target.programs[b])
            reports.append(rep)
        for blabel, bundle in sorted(
                getattr(target, "bundles", {}).items()):
            rep = TargetReport(f"{target.name}:bundle/{blabel}")
            rep.diagnostics = check_bundle(
                bundle, collect_suppressed=rep.suppressed)
            rep.liveness = liveness.bundle_liveness_facts(bundle)
            reports.append(rep)
    return reports


def _key(target: str, d: Diagnostic) -> str:
    return "|".join([target, d.code, d.severity, d.op_type or "",
                     d.var or ""])


def baseline_payload(reports: List[TargetReport]) -> dict:
    """The committed snapshot: gated (error/warning) finding counts
    per stable key, suppression counts, info totals (recorded for
    context, never gated — info findings are hygiene, and their
    counts churn with every model tweak), the zoo's propagated
    sharding facts (``target|var`` -> spec description, stable names
    only — absint.stable_sharding_facts), and the zoo's pool
    OWNERSHIP facts (``target|pool`` -> proven access summary with
    the named allocator assumptions, plus a per-target
    ``@assumptions`` roll-up — absint.stable_ownership_facts): a
    propagation/provenance-rule change that silently re-lays-out or
    re-derives an annotated program shows up as a facts diff,
    drift-gated exactly like a new warning. The LIVENESS facts
    (``target|key`` -> While variant verdicts, release-obligation
    roll-ups, and per-bundle admission-capacity feasibility —
    liveness.stable_liveness_facts / bundle_liveness_facts) gate the
    same way: a progress proof that stops proving, an obligation that
    stops discharging, or a capacity margin that flips is drift.

    Reference counterpart: none (see diff_against_baseline)."""
    entries: Dict[str, int] = {}
    suppressed: Dict[str, int] = {}
    sharding: Dict[str, str] = {}
    ownership: Dict[str, str] = {}
    liveness: Dict[str, str] = {}
    n_err = n_warn = n_info = 0
    for rep in reports:
        for d in rep.diagnostics:
            if d.severity == ERROR:
                n_err += 1
            elif d.severity == WARNING:
                n_warn += 1
            elif d.severity == INFO:
                n_info += 1
            if d.severity in (ERROR, WARNING):
                k = _key(rep.target, d)
                entries[k] = entries.get(k, 0) + 1
        for d, _reason in rep.suppressed:
            k = _key(rep.target, d)
            suppressed[k] = suppressed.get(k, 0) + 1
        for var, desc in rep.sharding.items():
            sharding[f"{rep.target}|{var}"] = desc
        for var, desc in rep.ownership.items():
            ownership[f"{rep.target}|{var}"] = desc
        for var, desc in rep.liveness.items():
            liveness[f"{rep.target}|{var}"] = desc
    return {
        "version": 4,
        "entries": {k: entries[k] for k in sorted(entries)},
        "suppressed": {k: suppressed[k] for k in sorted(suppressed)},
        "sharding_facts": {k: sharding[k] for k in sorted(sharding)},
        "ownership_facts": {k: ownership[k]
                            for k in sorted(ownership)},
        "liveness_facts": {k: liveness[k] for k in sorted(liveness)},
        "totals": {"errors": n_err, "warnings": n_warn,
                   "infos": n_info, "targets": len(reports)},
    }


def diff_against_baseline(reports: List[TargetReport],
                          baseline: dict):
    """(new, resolved): `new` lists error/warning finding keys whose
    count EXCEEDS the baseline's (the CI failure set); `resolved`
    lists baseline keys now absent or reduced (print-and-refresh,
    never a failure).

    The SUPPRESSED section is diffed too: a new ``_pta_suppress``
    would otherwise bypass both --strict (run_checks drops the
    diagnostic) and the entries diff — a silent disappearance, the
    exact thing this module promises not to allow. A new suppression
    therefore FAILS the gate until the baseline is refreshed, which
    forces the suppression into the committed analysis_baseline.json
    diff where a reviewer sees it; once recorded, it never fails
    again (the escape hatch stays usable, just visible).

    Reference counterpart: none — a compile-time golden-diagnostic
    drift gate has no reference analogue."""
    payload = baseline_payload(reports)
    new = []
    resolved = []
    for section, tag in (("entries", ""),
                         ("suppressed", " [suppressed]")):
        current = payload[section]
        base = dict(baseline.get(section, {}))
        for k, n in current.items():
            extra = n - base.get(k, 0)
            if extra > 0:
                new.append(f"{k} (x{extra} new{tag})")
        for k, n in base.items():
            have = current.get(k, 0)
            if have < n:
                resolved.append(f"{k} (-{n - have}{tag})")
    # sharding_facts / ownership_facts: value-compared, not counted —
    # a CHANGED spec or access-proof summary is drift (a propagation
    # rule, annotation, or provenance-rule change re-derived the
    # zoo's layouts/proofs) and fails like a new warning until the
    # baseline refresh puts the new facts in front of a reviewer
    for section, what in (("sharding_facts", "sharding"),
                          ("ownership_facts", "ownership"),
                          ("liveness_facts", "liveness")):
        current = payload[section]
        base = dict(baseline.get(section, {}))
        for k, v in current.items():
            if k not in base:
                new.append(f"{k}={v} (new {what} fact)")
            elif base[k] != v:
                new.append(f"{k}={v} (was {base[k]}: {what} drift)")
        for k, v in base.items():
            if k not in current:
                resolved.append(f"{k} ({what} fact gone)")
    return sorted(new), sorted(resolved)


def default_baseline_path() -> str:
    """The committed snapshot lives at the repo root, next to the
    BENCH_SELF records."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.abspath(os.path.join(here, "..", "..",
                                        BASELINE_FILENAME))


def write_baseline(reports: List[TargetReport],
                   path: Optional[str] = None) -> str:
    """Snapshot the sweep to `path` (default: the committed repo-root
    file). Reference counterpart: none (see diff_against_baseline)."""
    path = path or default_baseline_path()
    with open(path, "w") as f:
        json.dump(baseline_payload(reports), f, indent=1,
                  sort_keys=True)
        f.write("\n")
    return path


def load_baseline(path: Optional[str] = None) -> dict:
    """Load a baseline snapshot (default: the committed repo-root
    file). Reference counterpart: none (see diff_against_baseline)."""
    path = path or default_baseline_path()
    with open(path) as f:
        return json.load(f)
