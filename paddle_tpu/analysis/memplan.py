"""Static per-device memory planner (PTA170's engine).

Prices a Program's device memory BEFORE any trace/compile, from IR
metadata alone, under the sharding domain's propagated ShardSpecs
(analysis/absint.py):

* **state** — every persistable the executor's state_in path feeds
  (params, optimizer moments, KV pools, slot counters: the walk
  mirrors core/executor.py `_analyze_block_py`, so the byte total
  matches the compiled executable's argument accounting EXACTLY —
  tests/test_memory_plan.py pins that against the XLA compiler's own
  ``compiled.memory_analysis().argument_size_in_bytes`` on the CPU
  backend, the r5-proven comparison surface);
* **feeds** — declared data vars at a chosen batch;
* **temps** — a peak-liveness estimate over the block schedule with
  in-place/fusion modeling for the elementwise family (XLA fuses
  elementwise chains and aliases same-size elementwise outputs, so a
  naive sum of all intermediates over-prices 2-5x; with the aliasing
  model the estimate lands within ~25% of
  ``memory_analysis().temp_size_in_bytes`` on the straight-line zoo
  programs the validation test pins). While/cond bodies contribute
  their own peak at the container's position.

Per-DEVICE bytes divide each var's sharded dims by the MeshConfig
axis size (ceil, XLA's shard sizing): a KV pool sharded
``{head_dim: "tp"}`` prices at ~1/tp per device — the ROADMAP's
sharded-serving capacity claim, now a checkable number instead of
arithmetic in a doc.

Dtype accounting canonicalizes like the runtime (x64 disabled:
int64/uint64/float64 narrow to their 32-bit forms) so planned bytes
are DEVICE bytes, not numpy bytes.

Pure Python over Program metadata: no jax, no tracing — a whole
model prices in milliseconds (module invariant shared with the rest
of analysis/).

Reference counterpart: reference contrib memory_usage_calc.py
estimated TOTAL bytes from var shapes alone (no sharding, no
liveness, no executor contract); this planner is that idea rebuilt
against the jit executor's actual argument/temp surfaces.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..core.program import Block, Program
from ..core.registry import EMPTY_VAR
from .absint import MeshConfig, REPLICATED_SPEC, ShardSpec

__all__ = ["VarPlan", "MemoryPlan", "build_plan", "canonical_dtype",
           "var_nbytes", "RNG_KEY_BYTES", "INPLACE_OP_TYPES"]

# the executor threads one PRNGKey (uint32[2]) through every step
RNG_KEY_BYTES = 8

# ops whose XLA lowering is elementwise enough that the output buffer
# aliases a dying same-size input (fusion / in-place elementwise):
# the temp estimator's aliasing model. Layout movers (reshape/
# transpose on contiguous buffers) behave the same way.
INPLACE_OP_TYPES = frozenset({
    "scale", "cast", "assign", "relu", "sigmoid", "tanh", "exp",
    "log", "sqrt", "square", "clip", "dropout", "softmax",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "reshape", "unsqueeze", "squeeze", "transpose", "brelu", "elu",
    "leaky_relu", "sum",
})

_CANON = {"int64": "int32", "uint64": "uint32", "float64": "float32"}


def canonical_dtype(dtype) -> np.dtype:
    """Device dtype under the runtime's x64-disabled canonicalization
    (jax default; CLAUDE.md r5: 'feed dtypes must be
    jax-canonicalized or parameter sizes mismatch')."""
    s = np.dtype(dtype).name
    return np.dtype(_CANON.get(s, s))


def _concrete_shape(shape, batch: int) -> Tuple[int, ...]:
    return tuple(batch if (d is None or d < 0) else int(d)
                 for d in (shape or ()))


def var_nbytes(var, batch: int) -> int:
    """Total device bytes of one var's full (unsharded) value."""
    if var is None or var.dtype is None:
        return 0
    shape = _concrete_shape(var.shape, batch)
    n = 1
    for d in shape:
        n *= d
    return n * canonical_dtype(var.dtype.value).itemsize


def _device_nbytes(var, batch: int, spec: ShardSpec,
                   mesh: Optional[MeshConfig]) -> int:
    """Per-device bytes under `spec`: each sharded dim divides by its
    mesh axis size (ceil — XLA pads the ragged shard)."""
    if var is None or var.dtype is None:
        return 0
    shape = list(_concrete_shape(var.shape, batch))
    if spec.placements:
        for d, a in spec.placements:
            if d < len(shape):
                size = mesh.size(a) if mesh is not None else 1
                shape[d] = math.ceil(shape[d] / max(size, 1))
    n = 1
    for d in shape:
        n *= d
    return n * canonical_dtype(var.dtype.value).itemsize


@dataclass(frozen=True)
class VarPlan:
    """One priced var."""
    name: str
    klass: str                  # "state" | "feed"
    shape: Tuple[int, ...]
    dtype: str
    bytes: int                  # full logical value
    device_bytes: int           # per-device under the spec
    spec: str                   # ShardSpec.describe()


@dataclass
class MemoryPlan:
    """The static plan: what `analyze(p).device_memory_plan()` and
    the CLI's ``--memory-plan`` surface."""
    program: Program
    batch: int
    mesh: Optional[MeshConfig]
    state: List[VarPlan] = field(default_factory=list)
    feeds: List[VarPlan] = field(default_factory=list)
    temp_bytes: int = 0
    temp_device_bytes: int = 0
    rng_bytes: int = RNG_KEY_BYTES
    # state_in names the planner could not price (no declared var)
    unsized: List[str] = field(default_factory=list)

    @property
    def state_bytes(self) -> int:
        return sum(v.bytes for v in self.state)

    @property
    def state_device_bytes(self) -> int:
        return sum(v.device_bytes for v in self.state)

    @property
    def feed_bytes(self) -> int:
        return sum(v.bytes for v in self.feeds)

    @property
    def feed_device_bytes(self) -> int:
        return sum(v.device_bytes for v in self.feeds)

    @property
    def argument_bytes(self) -> int:
        """What the compiled step's XLA argument accounting shows:
        state + feeds + the threaded PRNG key (exact-match surface,
        tests/test_memory_plan.py)."""
        return self.state_bytes + self.feed_bytes + self.rng_bytes

    @property
    def total_bytes(self) -> int:
        return self.state_bytes + self.feed_bytes + self.temp_bytes \
            + self.rng_bytes

    @property
    def total_device_bytes(self) -> int:
        return self.state_device_bytes + self.feed_device_bytes \
            + self.temp_device_bytes + self.rng_bytes

    def entry(self, name: str) -> Optional[VarPlan]:
        for v in self.state + self.feeds:
            if v.name == name:
                return v
        return None

    def state_device_bytes_matching(self, *substrings) -> int:
        """Per-device bytes of state vars whose name contains any of
        `substrings` — e.g. ('self_k', 'self_v') prices the KV pool."""
        return sum(v.device_bytes for v in self.state
                   if any(s in v.name for s in substrings))

    def state_bytes_matching(self, *substrings) -> int:
        return sum(v.bytes for v in self.state
                   if any(s in v.name for s in substrings))

    def summary(self) -> str:
        head = (f"batch={self.batch}"
                + (f" mesh={self.mesh.describe()}" if self.mesh
                   else ""))
        lines = [
            f"memory plan [{head}]:",
            f"  state  {self.state_bytes:>12} B total "
            f"({self.state_device_bytes} B/device, "
            f"{len(self.state)} vars)",
            f"  feeds  {self.feed_bytes:>12} B total "
            f"({self.feed_device_bytes} B/device, "
            f"{len(self.feeds)} vars)",
            f"  temps  {self.temp_bytes:>12} B peak "
            f"({self.temp_device_bytes} B/device)",
            f"  args   {self.argument_bytes:>12} B "
            f"(state+feeds+rng: the XLA argument surface)",
        ]
        if self.unsized:
            lines.append(f"  unsized: {self.unsized[:5]}")
        return "\n".join(lines)


def _state_and_feed_names(block: Block):
    """Mirror core/executor.py _analyze_block_py: names read before
    any write, minus declared data vars (feeds) — the executor's
    state_in surface. Declared data vars are the feed surface."""
    feeds = [v.name for v in block.vars.values() if v.is_data]
    feedset = set(feeds)
    produced = set(feedset)
    state_in: List[str] = []
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        for n in op.input_arg_names:
            if n == EMPTY_VAR or n in produced:
                continue
            produced.add(n)
            state_in.append(n)
        for n in op.output_arg_names:
            produced.add(n)
    return state_in, feeds


def _block_temp_peak(blk: Block, feeds: Set[str], keep: Set[str],
                     batch: int, spec_of, mesh, device: bool) -> int:
    """Peak live temp bytes over one block's schedule, with the
    elementwise aliasing model (module docstring); container ops add
    their sub-blocks' own peaks at their position."""
    buf_of: Dict[str, int] = {}
    bufs: Dict[int, list] = {}       # id -> [size, birth, death]
    reads: Dict[str, int] = {}
    for i, op in enumerate(blk.ops):
        for n in op.input_arg_names:
            if n != EMPTY_VAR:
                reads[n] = i
    next_id = [0]
    sub_peaks: Dict[int, int] = {}
    for i, op in enumerate(blk.ops):
        if op.type in ("feed", "fetch"):
            continue
        for v in op.attrs.values():
            if isinstance(v, Block):
                sub_peaks[i] = sub_peaks.get(i, 0) + _block_temp_peak(
                    v, feeds, keep, batch, spec_of, mesh, device)
        for n in op.output_arg_names:
            if n == EMPTY_VAR:
                continue
            if n in buf_of:
                b = bufs[buf_of[n]]
                b[2] = max(b[2], reads.get(n, i))
                continue
            var = blk._find_var_recursive(n)
            if var is None or var.persistable or var.is_data \
                    or n in feeds or n in keep:
                continue
            size = _device_nbytes(var, batch, spec_of(n), mesh) \
                if device else var_nbytes(var, batch)
            alias = None
            if op.type in INPLACE_OP_TYPES:
                for m in op.input_arg_names:
                    b = buf_of.get(m)
                    if b is not None and bufs[b][0] == size \
                            and reads.get(m, -1) == i:
                        alias = b
                        break
            if alias is not None:
                buf_of[n] = alias
                bufs[alias][2] = max(bufs[alias][2], reads.get(n, i))
            else:
                bid = next_id[0]
                next_id[0] += 1
                buf_of[n] = bid
                bufs[bid] = [size, i, reads.get(n, i)]
    peak = 0
    for i in range(len(blk.ops)):
        live = sum(s for s, b, d in bufs.values() if b <= i <= d)
        live += sub_peaks.get(i, 0)
        peak = max(peak, live)
    return peak


def build_plan(facts, batch: int = 1,
               fetch_names: Tuple[str, ...] = ()) -> MemoryPlan:
    """Build the plan from ProgramFacts (the cached absint fixpoint:
    the specs are already propagated). `batch` substitutes dynamic
    (-1) dims; `fetch_names` are excluded from the temp estimate
    (XLA prices fetched values as outputs, not temps)."""
    program = facts.program
    mesh = facts.mesh
    block = program.global_block
    state_in, feed_names = _state_and_feed_names(block)
    plan = MemoryPlan(program, batch, mesh)
    for name in state_in:
        var = block._find_var_recursive(name)
        if var is None or var.dtype is None:
            plan.unsized.append(name)
            continue
        spec = facts.spec(name)
        plan.state.append(VarPlan(
            name, "state", _concrete_shape(var.shape, batch),
            canonical_dtype(var.dtype.value).name,
            var_nbytes(var, batch),
            _device_nbytes(var, batch, spec, mesh),
            spec.describe()))
    for name in feed_names:
        var = block._find_var_recursive(name)
        if var is None or var.dtype is None:
            plan.unsized.append(name)
            continue
        spec = facts.spec(name)
        plan.feeds.append(VarPlan(
            name, "feed", _concrete_shape(var.shape, batch),
            canonical_dtype(var.dtype.value).name,
            var_nbytes(var, batch),
            _device_nbytes(var, batch, spec, mesh),
            spec.describe()))
    # temps: persistable outputs (state_out) and fetches are not temp
    keep = {n for op in block.ops for n in op.output_arg_names
            if n != EMPTY_VAR and (
                (block._find_var_recursive(n) or _NoVar).persistable)}
    keep |= set(fetch_names)
    feedset = set(feed_names)
    plan.temp_bytes = _block_temp_peak(
        block, feedset, keep, batch, facts.spec, mesh, device=False)
    plan.temp_device_bytes = _block_temp_peak(
        block, feedset, keep, batch, facts.spec, mesh, device=True)
    return plan


class _NoVar:
    persistable = False
