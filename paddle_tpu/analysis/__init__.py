"""Program verifier: static analysis over the Program IR.

The reference validates programs in C++ BEFORE execution
(reference paddle/fluid/framework/op_desc.cc CheckAttrs + each op's
InferShape, operator.cc:975 RunImpl enforcement); the whole-block-jit
Executor here compiles the entire block in one shot and had no
equivalent gate — malformed programs surfaced as multi-hour trace
debugging or a wedged TPU tunnel. This package is that gate, in the
shape of TVM's Relay well-formedness passes / TensorFlow's GraphDef
validators (PAPERS.md): a millisecond-scale diagnostics engine over
the program-as-data IR.

Pieces:

* analysis.dataflow — def-use chains per block + recursive sub-block
  walking (the `_scan_fallback_reason` walk, generalized), with an
  explicit per-op-type registry of sub-block entry-name attrs.
* analysis.absint — the divergence & sharding prover: whole-program
  fixpoint abstract interpretation (divergence contexts, the
  replicated/varying/unknown lattice, declared-vs-producer
  shape/dtype facts, and the SHARDING DOMAIN — per-op ShardSpec
  propagation over the rules registered in core/registry.py, seeded
  from mark_sharded/MeshConfig annotations) feeding
  PTA130/131/140/160/161/170, plus the divergence-source seed table
  sharded lowerings register with.
* analysis.sharding_rules — the per-op-family propagation rules
  (matmul/mul contraction psums, reshape major-dim carry, reduce
  psums, gather allgathers, elementwise conflicts, ...); unknown ops
  degrade to an explicit ⊤ spec with a warn-once.
* analysis.ownership_rules — the pool-index PROVENANCE rules behind
  the ownership domain (absint ProvFact: host-owned source tags with
  typestates, constants, one-hot indicators, value bounds, through
  the affine/selection idioms the paged lowerings use), feeding
  PTA190 (provenance + in-bounds), PTA191 (lane-exclusive write
  PROVEN under the named host-allocator assumption — subsumes
  PTA110's declaration) and PTA192 (read-only-while-shared, the COW
  contract); ops without a rule propagate nothing, so an unproven
  index fails loudly at the pool access.
* analysis.liveness — the protocol LIVENESS domain: admission-
  capacity feasibility (PTA200 — a declarative resource model over
  the host allocators; session-pinned prompt entries against
  never-closing sessions is the canonical infeasible witness),
  release-on-every-exit-path obligation ledgers (PTA201 — every
  acquire contract registered via absint.register_acquire_release
  must name a release site for each declared exit path), and While
  progress variants (PTA202 — a bounded increment-driven counter in
  the condition's backward slice; serve loops additionally carry the
  named monotone-lane_active_mask assumption).
* analysis.protomodel — the exhaustive bounded model checker over
  the HOST allocator typestate machines (HostBlockPool,
  PromptPrefixCache, RadixBlockTree, session pin/unpin): BFS over
  small-bound state spaces with refcount-conservation invariants,
  drain-to-free leak checks, deadlock detection and minimal
  counterexample traces — the oracle PTA200's feasibility predicate
  is validated against (tests/test_protomodel.py grid).
* analysis.memplan — the static per-device memory planner behind
  ``analyze(p).device_memory_plan()`` / CLI ``--memory-plan`` /
  checker PTA170: persistable/feed/temp bytes under the propagated
  specs, validated against ``compiled.memory_analysis()``.
* analysis.checkers — the Checker registry: stable `PTA0xx` codes,
  severity error/warn/info, op/var anchors, fix hints. Every checker
  encodes a REAL incident from CLAUDE.md's session learnings
  (collective-in-divergent-cond deadlocks, int->float while-carry
  promotion, _uid loss, global-counter param names, ...). Bundle-
  level contracts ride `check_bundle` (PTA150); per-site
  suppressions ride the ``_pta_suppress`` op attr (counted,
  surfaced).
* Executor gate — ``FLAGS_static_check={off,warn,strict}`` runs the
  suite before every compile (strict raises EnforceNotMet with the
  diagnostic list).
* CLI — ``python -m paddle_tpu.analysis`` builds and lints every
  program in models/ and benchmark/ (``--strict`` for CI;
  ``--baseline`` diffs the zoo's diagnostic set against the
  committed analysis_baseline.json and fails on any NEW
  error-or-warning — analysis.baseline has the machinery).

Usage::

    from paddle_tpu import analysis
    diags = analysis.run_checks(program)         # all checkers
    errs = [d for d in diags if d.severity == analysis.ERROR]
    analysis.check_shared_params(train_prog, decode_prog)
    analysis.check_clone_uids(prog, prog.clone())
"""
from __future__ import annotations

from typing import List

from . import absint, liveness, protomodel
from .checkers import (Checker, Diagnostic, ERROR, INFO, WARNING,
                       SUPPRESS_ATTR, check_bundle, check_clone_uids,
                       check_cross_model_collision,
                       check_registry, check_shared_params,
                       format_diagnostics, register_checker,
                       registered_checkers, run_checks)
from .dataflow import (BlockDataflow, OpSite, analyze_block,
                       iter_blocks, iter_ops, iter_sub_blocks,
                       register_block_entry_attrs)

__all__ = [
    "Diagnostic", "Checker", "ERROR", "WARNING", "INFO",
    "run_checks", "register_checker", "registered_checkers",
    "check_registry", "check_shared_params", "check_clone_uids",
    "check_cross_model_collision", "check_bundle", "SUPPRESS_ATTR",
    "format_diagnostics", "maybe_check_program", "absint",
    "liveness", "protomodel",
    "BlockDataflow", "OpSite", "analyze_block", "iter_blocks",
    "iter_ops", "iter_sub_blocks", "register_block_entry_attrs",
]

# one gate evaluation per (program uid, version): the Executor calls
# maybe_check_program on every compile, and one program compiles many
# specializations (feed-shape buckets, AMP tokens) — the diagnostics
# only change when the PROGRAM does (Pass.apply bumps _version)
_checked_cache: dict = {}


def maybe_check_program(program) -> List[Diagnostic]:
    """The Executor's pre-compile gate (core/executor.py
    _build_step_fn): honors FLAGS_static_check. off -> no-op;
    warn -> warnings.warn with the error/warning diagnostics;
    strict -> raise EnforceNotMet when any ERROR diagnostic fires."""
    from ..flags import FLAGS

    mode = FLAGS.static_check
    if mode == "off":
        return []
    key = (getattr(program, "_uid", id(program)),
           getattr(program, "_version", 0), mode)
    cached = _checked_cache.get(key)
    if cached is None:
        cached = run_checks(program)
        if len(_checked_cache) > 512:
            _checked_cache.clear()
        _checked_cache[key] = cached
    errors = [d for d in cached if d.severity == ERROR]
    warns = [d for d in cached if d.severity == WARNING]
    if errors and mode == "strict":
        from ..enforce import EnforceNotMet

        raise EnforceNotMet(
            f"FLAGS_static_check=strict: program verifier found "
            f"{len(errors)} error(s):\n"
            + format_diagnostics(errors))
    if errors or warns:
        import warnings

        warnings.warn(
            f"static_check: {len(errors)} error(s), {len(warns)} "
            f"warning(s) in program:\n"
            + format_diagnostics(errors + warns))
    return cached
