"""CLI: build and lint every models/ + benchmark/ program.

Usage:
    python -m paddle_tpu.analysis [--strict] [--json] [--verbose]
                                  [--only mnist transformer ...]
                                  [--no-benchmark] [--registry]

Exit status: 0 clean (no error-severity diagnostics), 2 when any
program has errors (or, with --strict-warn, warnings). This is the
CI gate ISSUE 3 asks for: regressions in program builders fail here
in seconds instead of on-chip.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser("python -m paddle_tpu.analysis")
    p.add_argument("--strict", action="store_true",
                   help="exit 2 if any error diagnostic fires")
    p.add_argument("--strict-warn", action="store_true",
                   help="exit 2 on warnings too")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON object")
    p.add_argument("--verbose", action="store_true",
                   help="print info-severity diagnostics as well")
    p.add_argument("--only", nargs="*", default=None,
                   help="models/ names to lint (default: everything; "
                        "note --only also skips the benchmark/ sweep)")
    p.add_argument("--no-benchmark", action="store_true",
                   help="skip the benchmark/ harness programs")
    p.add_argument("--registry", action="store_true",
                   help="also sweep the FULL op registry for host_"
                        "effect completeness (PTA070)")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")  # lint never needs a TPU

    from . import (ERROR, INFO, WARNING, check_cross_model_collision,
                   check_registry, check_shared_params, run_checks)
    from .targets import MODEL_BUILDERS, iter_lint_targets

    pair_checkers = {"shared_params": check_shared_params,
                     "cross_model": check_cross_model_collision}

    if args.only:
        unknown = sorted(set(args.only) - set(MODEL_BUILDERS))
        if unknown:
            # a typo'd --only must NOT look like a green strict run
            print(f"error: unknown --only name(s) {unknown}; known: "
                  f"{sorted(MODEL_BUILDERS)}", file=sys.stderr)
            return 2

    report = []
    n_err = n_warn = 0
    for target in iter_lint_targets(
            include_benchmark=not args.no_benchmark, only=args.only):
        for label, prog in target.programs.items():
            diags = run_checks(prog)
            pair_check = pair_checkers[target.pair_check]
            for a, b in target.pairs:
                if label == a:
                    diags = diags + pair_check(
                        target.programs[a], target.programs[b])
            errs = [d for d in diags if d.severity == ERROR]
            warns = [d for d in diags if d.severity == WARNING]
            infos = [d for d in diags if d.severity == INFO]
            n_err += len(errs)
            n_warn += len(warns)
            report.append({
                "target": f"{target.name}:{label}",
                "errors": [d.format() for d in errs],
                "warnings": [d.format() for d in warns],
                "infos": len(infos) if not args.verbose
                else [d.format() for d in infos],
            })
            if not args.json:
                status = "OK" if not (errs or warns) else \
                    f"{len(errs)} error(s), {len(warns)} warning(s)"
                print(f"{target.name}:{label}: {status} "
                      f"({len(infos)} info)")
                for d in errs + warns:
                    print("  " + d.format().replace("\n", "\n  "))
                if args.verbose:
                    for d in infos:
                        print("  " + d.format().replace("\n", "\n  "))

    if args.registry:
        regs = check_registry()
        n_err += len(regs)
        report.append({"target": "registry",
                       "errors": [d.format() for d in regs],
                       "warnings": [], "infos": 0})
        if not args.json:
            print(f"registry: "
                  f"{'OK' if not regs else f'{len(regs)} error(s)'}")
            for d in regs:
                print("  " + d.format().replace("\n", "\n  "))

    if args.json:
        print(json.dumps({"targets": report, "errors": n_err,
                          "warnings": n_warn}, indent=1))
    else:
        print(f"TOTAL: {n_err} error(s), {n_warn} warning(s) across "
              f"{len(report)} program(s)")
    if args.strict and n_err:
        return 2
    if args.strict_warn and (n_err or n_warn):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
