"""CLI: build and lint every models/ + benchmark/ program.

Usage:
    python -m paddle_tpu.analysis [--strict] [--json] [--verbose]
                                  [--only mnist transf ...]
                                  [--no-benchmark] [--registry]
                                  [--memory-plan]
                                  [--baseline [PATH]]
                                  [--write-baseline [PATH]]
                                  [--explain PTA0xx ...]

``--explain PTA0xx`` prints the named checker's contract docstring
(what it proves, what a finding means, how to discharge or suppress
it) straight from the registered checker — no zoo build.

``--only`` filters by target-name SUBSTRING (``--only transf`` lints
models/transformer), so iterating on one checker against one program
stops costing a full zoo build; ``--json`` carries per-checker wall
seconds (``checker_seconds``) so a slow checker is attributable;
``--memory-plan`` prints each program's static per-device memory
plan (analysis/memplan.py — the PTA170 surface).

Exit status: 0 clean, 2 when any program has error diagnostics (or,
with --strict-warn, warnings; or, with --baseline, any error-or-
warning NEW vs the committed analysis_baseline.json — the CI drift
gate, which also value-diffs the ``sharding_facts`` snapshot). This
is the gate ISSUE 3 asked for and ISSUE 11 hardened: builder
regressions fail here in seconds instead of on-chip, and once
warnings gate CI the baseline pins the full diagnostic set.
"""
from __future__ import annotations

import argparse
import json
import sys


def _explain(codes) -> int:
    """Print each named checker's contract docstring — the catalog's
    tribal knowledge, surfaced at the CLI so a red finding comes with
    its own discharge instructions. Unknown codes exit 2 (a typo'd
    code must not look like a documented one)."""
    import inspect

    from .checkers import registered_checkers

    by_code = {c.code: c for c in registered_checkers()}
    rc = 0
    for raw in codes:
        code = raw.upper()
        chk = by_code.get(code)
        if chk is None:
            print(f"error: unknown checker code {raw!r}; known: "
                  f"{' '.join(sorted(by_code))}", file=sys.stderr)
            rc = 2
            continue
        doc = inspect.cleandoc(chk.doc) if chk.doc \
            else "(no contract docstring registered)"
        print(f"{chk.code} — {chk.name}\n")
        print(doc)
        print(f"\nsuppress: attach _pta_suppress=(\"{chk.code}\", "
              f"\"<reason>\") at the flagged op (bundle-level codes "
              f"like PTA150/PTA200: set bundle._pta_suppress); every "
              f"suppression is counted and drift-gated by "
              f"analysis_baseline.json, never silent.\n")
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser("python -m paddle_tpu.analysis")
    p.add_argument("--strict", action="store_true",
                   help="exit 2 if any error diagnostic fires")
    p.add_argument("--strict-warn", action="store_true",
                   help="exit 2 on warnings too")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON object")
    p.add_argument("--verbose", action="store_true",
                   help="print info-severity diagnostics as well")
    p.add_argument("--only", nargs="*", default=None,
                   help="target-name SUBSTRINGS to lint (e.g. "
                        "'transf' lints models/transformer; default: "
                        "everything; note --only also skips the "
                        "benchmark/ sweep)")
    p.add_argument("--no-benchmark", action="store_true",
                   help="skip the benchmark/ harness programs")
    p.add_argument("--registry", action="store_true",
                   help="also sweep the FULL op registry for host_"
                        "effect completeness (PTA070)")
    p.add_argument("--memory-plan", action="store_true",
                   help="print each program's static per-device "
                        "memory plan (PTA170's planner; --json adds "
                        "a memory_plan section per target)")
    p.add_argument("--baseline", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="diff the sweep against the committed "
                        "baseline snapshot (default: repo-root "
                        "analysis_baseline.json); exit 2 on any NEW "
                        "error-or-warning")
    p.add_argument("--write-baseline", nargs="?", const="",
                   default=None, metavar="PATH",
                   help="(re)write the baseline snapshot from this "
                        "sweep and exit 0")
    p.add_argument("--explain", nargs="+", default=None,
                   metavar="PTA0xx",
                   help="print the named checker(s)' contract "
                        "docstring and suppression convention, then "
                        "exit (skips the zoo build)")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")  # lint never needs a TPU

    if args.explain is not None:
        return _explain(args.explain)

    from . import ERROR, INFO, WARNING, check_registry
    from .baseline import (collect_reports, diff_against_baseline,
                           load_baseline, write_baseline)
    from .targets import MODEL_BUILDERS

    if args.only:
        from .targets import match_targets

        matched = match_targets(args.only)
        if not matched:
            # a typo'd --only must NOT look like a green strict run
            print(f"error: --only {args.only} matches no target; "
                  f"known: {sorted(MODEL_BUILDERS)}", file=sys.stderr)
            return 2
    if args.baseline is not None or args.write_baseline is not None:
        # the drift gate (and the snapshot it diffs against) is only
        # meaningful over the FULL zoo: a shrunk sweep hides new
        # findings as vacuous 'resolved' entries
        flag = "--baseline" if args.baseline is not None \
            else "--write-baseline"
        if args.only or args.no_benchmark:
            print(f"error: {flag} covers the FULL zoo; drop "
                  f"--only/--no-benchmark", file=sys.stderr)
            return 2

    checker_seconds = {}
    reports = collect_reports(
        include_benchmark=not args.no_benchmark, only=args.only,
        collect_timings=checker_seconds,
        with_plans=args.memory_plan)

    report = []
    n_err = n_warn = n_sup = 0
    for rep in reports:
        errs = rep.by_severity(ERROR)
        warns = rep.by_severity(WARNING)
        infos = rep.by_severity(INFO)
        n_err += len(errs)
        n_warn += len(warns)
        n_sup += len(rep.suppressed)
        entry = {
            "target": rep.target,
            "errors": [d.format() for d in errs],
            "warnings": [d.format() for d in warns],
            "infos": len(infos) if not args.verbose
            else [d.format() for d in infos],
        }
        if rep.suppressed:
            entry["suppressed"] = [
                {"code": d.code, "severity": d.severity,
                 "reason": reason, "diagnostic": d.format()}
                for d, reason in rep.suppressed]
        if rep.ownership:
            # the obligations/assumptions ledger: which named host-
            # allocator invariants this target's pool proofs rest on
            # (PTA190/191/192 — the ownership prover surface)
            entry["ownership"] = {
                "facts": dict(rep.ownership),
                "ledger": dict(rep.ownership_ledger),
            }
        if rep.liveness:
            # the release-obligation / progress ledger: which acquire
            # contracts this target discharges on every exit path and
            # which While loops carry a proven variant (PTA200/201/202
            # — the liveness prover surface)
            entry["liveness"] = {
                "facts": dict(rep.liveness),
                "ledger": dict(rep.liveness_ledger),
            }
        if args.memory_plan and rep.plan is not None:
            entry["memory_plan"] = {
                "state_bytes": rep.plan.state_bytes,
                "state_device_bytes": rep.plan.state_device_bytes,
                "feed_bytes": rep.plan.feed_bytes,
                "temp_bytes": rep.plan.temp_bytes,
                "temp_device_bytes": rep.plan.temp_device_bytes,
                "argument_bytes": rep.plan.argument_bytes,
                "total_device_bytes": rep.plan.total_device_bytes,
                "mesh": rep.plan.mesh.describe()
                if rep.plan.mesh else None,
            }
        report.append(entry)
        if not args.json:
            status = "OK" if not (errs or warns) else \
                f"{len(errs)} error(s), {len(warns)} warning(s)"
            sup = f", {len(rep.suppressed)} suppressed" \
                if rep.suppressed else ""
            print(f"{rep.target}: {status} ({len(infos)} info{sup})")
            if args.memory_plan and rep.plan is not None:
                print("  " + rep.plan.summary().replace("\n", "\n  "))
            for d in errs + warns:
                print("  " + d.format().replace("\n", "\n  "))
            for d, reason in rep.suppressed:
                print(f"  suppressed {d.code} [{d.severity}]: "
                      f"{reason}")
            if args.verbose:
                for d in infos:
                    print("  " + d.format().replace("\n", "\n  "))

    if args.registry:
        regs = check_registry()
        n_err += len(regs)
        report.append({"target": "registry",
                       "errors": [d.format() for d in regs],
                       "warnings": [], "infos": 0})
        if not args.json:
            print(f"registry: "
                  f"{'OK' if not regs else f'{len(regs)} error(s)'}")
            for d in regs:
                print("  " + d.format().replace("\n", "\n  "))

    baseline_result = None
    if args.write_baseline is not None:
        path = write_baseline(reports, args.write_baseline or None)
        if not args.json:
            print(f"baseline written: {path}")
    elif args.baseline is not None:
        base = load_baseline(args.baseline or None)
        new, resolved = diff_against_baseline(reports, base)
        baseline_result = {"new": new, "resolved": resolved}
        if not args.json:
            for k in new:
                print(f"BASELINE: NEW finding {k}")
            for k in resolved:
                print(f"baseline: resolved {k} — refresh with "
                      f"--write-baseline")

    if args.json:
        # zoo-wide assumptions/obligations roll-up: every named host
        # invariant the ownership proofs lean on, with site counts —
        # reviewable next to the per-checker wall seconds
        assumptions, obligations = {}, {}
        for rep in reports:
            led = rep.ownership_ledger or {}
            for name, n in (led.get("assumptions") or {}).items():
                assumptions[name] = assumptions.get(name, 0) + n
            for name, n in (led.get("obligations") or {}).items():
                obligations[name] = obligations.get(name, 0) + n
        # zoo-wide liveness roll-up: total discharged release
        # obligations and every UNDISCHARGED one by target — the
        # "zero unproven" acceptance surface the gate test asserts
        liv_proven = 0
        liv_unproven = []
        for rep in reports:
            led = rep.liveness_ledger or {}
            liv_proven += int(led.get("proven", 0))
            liv_unproven += [f"{rep.target}: {u}"
                             for u in led.get("unproven", [])]
        out = {"targets": report, "errors": n_err,
               "warnings": n_warn, "suppressed": n_sup,
               "ownership_ledger": {
                   "assumptions": dict(sorted(assumptions.items())),
                   "obligations": dict(sorted(obligations.items()))},
               "liveness_ledger": {
                   "proven": liv_proven,
                   "unproven": sorted(liv_unproven)},
               "checker_seconds": {
                   k: round(v, 4)
                   for k, v in sorted(checker_seconds.items())}}
        if baseline_result is not None:
            out["baseline"] = baseline_result
        print(json.dumps(out, indent=1))
    else:
        print(f"TOTAL: {n_err} error(s), {n_warn} warning(s), "
              f"{n_sup} suppressed across {len(report)} program(s)")
    if args.strict and n_err:
        return 2
    if args.strict_warn and (n_err or n_warn):
        return 2
    if baseline_result is not None and baseline_result["new"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
