"""Pool-index provenance rules for the core op families.

The ownership domain (analysis/absint.py) proves where every index
reaching a ``@POOL`` read/write COMES FROM: a host-owned table mark
(``mark_pool_index_source``), a trace-time constant, or a composition
of those through the affine / one-hot-selection idioms the paged
lowerings actually use (models/decode_engine.py: block-table cell
addressing is ``tab[lane, p//BS]*BS + p%BS`` built from cast/scale/
expand/add; the current write cell is a one-hot page/offset selection
``reduce_sum(tab * onehot)``). Each rule states how one op family
carries a ProvFact (source tags, constness, 0/1 indicators,
one-hotness, value bounds) from inputs to outputs.

Rules register through ``core.registry.register_index_rule`` —
beside the sharding rules — so an op that joins an index-composition
path registers its provenance fact where it registers its kernel
(CLAUDE.md conventions). Ops WITHOUT a rule propagate NOTHING: an
index flowing through one reaches the pool access with UNKNOWN
provenance and PTA190 rejects it loudly — imprecision can only cause
false alarms at annotated pool accesses, never a silent pass.

Bound semantics: ``bound`` is an EXCLUSIVE upper bound on integer
values; the sub/mul/scale bound arithmetic is only sound over
non-negative operands, so signs are TRACKED (``ProvFact.nonneg``):
negative constants mint no fact at all, subtraction drops the bound
unless the subtrahend is provably >= 0 and marks its own result
possibly-negative, and products/selections require non-negative
operands before certifying a bound. (Negative indices at a WRITE are
clamped into the trash row by the masked_pool_write kernel,
ops/paged_ops.py; reads have no such net — which is why the read
bound proof must not lie.)
One-hot semantics: ``onehot`` promises at most one nonzero in each
ROW's trailing block — the mint is ``equal(distinct 1-D constant,
broadcast scalar-per-row)`` with the broadcast SHAPE checked, reshape
preserves it (the row axis stays leading), transpose DROPS it (the
row axis moves), and a reduce_sum over non-leading axes of a per-row
one-hot stays 0/1-valued — which is what lets a selector product
(``selection``) keep the selected operand's tags and bound through
the contraction, and only then.

Rule contract::

    rule(op, prov_of, shape_of) -> {output var name: ProvFact}

``prov_of(name) -> Optional[ProvFact]`` (None = no provenance known),
``shape_of(name) -> tuple | None``. Rules are PURE metadata functions:
no jax, no tracing.

Reference counterpart: none — the reference checks allocator state at
runtime (reference framework/scope.cc, memory/allocation); the
compile-time provenance algebra is the shared-pool serving capability
this framework adds (vLLM SOSP'23 block tables, machine-checked).
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import numpy as np

from ..core.registry import EMPTY_VAR, register_index_rule
from .absint import ProvFact, prov_join

__all__ = ["INDEX_RULE_FAMILIES"]

# family name -> op types it covers (documentation + the tests'
# enumeration; the actual registry is core.registry's)
INDEX_RULE_FAMILIES: Dict[str, Tuple[str, ...]] = {}


def _family(name, op_types):
    INDEX_RULE_FAMILIES[name] = tuple(op_types)

    def deco(fn):
        register_index_rule(op_types, fn)
        return fn

    return deco


def _outs(op):
    return [n for n in op.output_arg_names if n != EMPTY_VAR]


def _in(op, slot, idx=0):
    names = op.inputs.get(slot) or []
    return names[idx] if len(names) > idx else None


def _all_outs(op, fact):
    if fact is None:
        return {}
    return {n: fact for n in _outs(op)}


def _step(fact, op):
    return fact.with_step(op.type) if fact is not None else None


def _chain(base, op_type):
    """Extend a provenance chain under the same 8-entry cap
    ProvFact.with_step enforces (rules that construct ProvFact
    directly must not bypass it — an unbounded chain bloats the
    cached facts and the printed diagnostics alike)."""
    return base if len(base) >= 8 else base + (op_type,)


# --- constant mints ---------------------------------------------------------
# Negative-valued constants mint NO fact at all: the non-negative
# index domain is what makes the sub/mul/scale bound arithmetic
# sound, and a negative constant reaching an index slot should fail
# the provenance proof loudly rather than carry a lying bound.
@_family("const-fill", ("fill_constant", "fill_zeros_like"))
def _fill_constant(op, prov_of, shape_of):
    v = op.attrs.get("value", 0.0)
    try:
        v = float(v)
    except (TypeError, ValueError):
        return {}
    if op.type == "fill_zeros_like":
        v = 0.0
    if v < 0:
        return {}
    bound = int(v) + 1 if float(v).is_integer() else None
    return _all_outs(op, ProvFact(
        const=True, bound=bound, indicator=v in (0.0, 1.0),
        chain=(f"{op.type}({v})",)))


@_family("const-values", ("assign_value",))
def _assign_value(op, prov_of, shape_of):
    vals = op.attrs.get("values")
    try:
        arr = np.asarray(vals, dtype="float64").ravel()
    except (TypeError, ValueError):
        return {}
    if not arr.size or float(arr.min()) < 0:
        return {}
    bound = int(math.floor(float(arr.max()))) + 1
    fact = ProvFact(
        const=True, bound=bound,
        indicator=bool(np.isin(arr, (0.0, 1.0)).all()),
        distinct=bool(np.unique(arr).size == arr.size),
        chain=("assign_value",))
    return _all_outs(op, fact)


@_family("const-range", ("range",))
def _range(op, prov_of, shape_of):
    start = op.attrs.get("start")
    end = op.attrs.get("end")
    step = op.attrs.get("step")
    if not all(isinstance(v, (int, float))
               for v in (start, end, step)):
        return {}   # Variable bounds: host values unknown at lint
    if step <= 0 or start < 0:
        return {}   # descending/negative ranges leave the domain
    bound = max(1, int(math.ceil(end)))
    return _all_outs(op, ProvFact(
        const=True, distinct=True, bound=bound,
        chain=(f"range({start},{end},{step})",)))


# --- value-preserving views / copies ----------------------------------------
@_family("identity", (
        "cast", "assign", "unsqueeze", "unsqueeze2", "squeeze",
        "squeeze2", "stop_gradient"))
def _identity(op, prov_of, shape_of):
    src = _in(op, "X")
    return _all_outs(op, _step(prov_of(src) if src else None, op))


def _prod(dims):
    n = 1
    for d in dims:
        if d is None or d < 0:
            return None
        n *= int(d)
    return n


@_family("reshape", ("reshape", "reshape2"))
def _reshape(op, prov_of, shape_of):
    # per-element properties always survive; the per-row one-hot/
    # selection block survives ONLY a reshape that keeps the leading
    # (row) dims intact and re-factors the trailing block — a
    # reshape folding rows INTO the block ([A, R] -> [A*R]) piles
    # A nonzeros into one block and the <=1 claim breaks
    src = _in(op, "X")
    f = prov_of(src) if src else None
    if f is None:
        return {}
    if not (f.onehot or f.selection):
        return _all_outs(op, f.with_step(op.type))
    si = shape_of(src)
    outs = _outs(op)
    so = shape_of(outs[0]) if outs else None
    keep = False
    tail = 0
    if si is not None and so is not None and \
            0 < f.oh_tail <= len(si):
        lead = tuple(si[:len(si) - f.oh_tail])
        if tuple(so[:len(lead)]) == lead and len(so) > len(lead):
            tail = len(so) - len(lead)
            ip = _prod(si[len(lead):])
            op_ = _prod(so[len(lead):])
            keep = ip is not None and ip == op_
    return _all_outs(op, f.with_step(
        op.type, onehot=f.onehot and keep,
        selection=f.selection and keep,
        oh_tail=tail if keep else 0))


@_family("transpose", ("transpose", "transpose2"))
def _transpose(op, prov_of, shape_of):
    # per-ELEMENT properties survive a permutation; the per-row
    # one-hot/selection structure does not (moving the row axis off
    # the front lets later trailing-axis reduces sum ACROSS rows —
    # the admission ohT [A,rows]->[rows,A] case, where the dustbin
    # row holds many nonzeros)
    f = prov_of(_in(op, "X") or "")
    if f is None:
        return {}
    return _all_outs(op, f.with_step(op.type, onehot=False,
                                     selection=False, oh_tail=0))


@_family("expand", ("expand",))
def _expand(op, prov_of, shape_of):
    f = prov_of(_in(op, "X") or "")
    if f is None:
        return {}
    # tiling repeats entries: per-VALUE properties survive, pairwise
    # distinctness does not, and neither does the one-hot block
    # (tiling along the block duplicates its nonzero)
    return _all_outs(op, f.with_step(op.type, distinct=False,
                                     onehot=False, selection=False,
                                     oh_tail=0))


@_family("gather", ("gather", "gather_nd"))
def _gather(op, prov_of, shape_of):
    # output VALUES come from X (a subset, possibly repeated): tags/
    # bound/constness survive, distinctness does not. Plain gather
    # selects whole axis-0 rows, so a per-row one-hot block rides
    # along; gather_nd may index INTO the block (its last-axis
    # components address several leading axes), so the structural
    # claims drop there. The gather's own Index is judged at the
    # pool-access record when X is a pool view
    # (absint._record_pool_access), not here.
    f = prov_of(_in(op, "X") or "")
    if f is None:
        return {}
    if op.type == "gather_nd":
        return _all_outs(op, f.with_step(op.type, distinct=False,
                                         onehot=False,
                                         selection=False,
                                         oh_tail=0))
    return _all_outs(op, f.with_step(op.type, distinct=False))


@_family("split", ("split",))
def _split(op, prov_of, shape_of):
    f = prov_of(_in(op, "X") or "")
    if f is None:
        return {}
    # splitting can cut THROUGH the one-hot block: drop the
    # structural claims, keep the per-element ones
    return _all_outs(op, f.with_step(op.type, distinct=False,
                                     onehot=False, selection=False,
                                     oh_tail=0))


@_family("concat", ("concat",))
def _concat(op, prov_of, shape_of):
    facts = [prov_of(n) for n in op.input_arg_names
             if n != EMPTY_VAR]
    if not facts or any(f is None for f in facts):
        return {}
    out = facts[0]
    for f in facts[1:]:
        out = prov_join(out, f)
    # prov_join's both-sides-keep-it semantics is for ALTERNATIVE
    # writers; concatenated values COEXIST — two per-row one-hots
    # glued along the block hold two nonzeros per row, so the
    # structural claims never survive a concat
    return _all_outs(op, out.with_step(op.type, distinct=False,
                                       onehot=False,
                                       selection=False, oh_tail=0))


# --- affine arithmetic ------------------------------------------------------
@_family("scale", ("scale",))
def _scale(op, prov_of, shape_of):
    f = prov_of(_in(op, "X") or "")
    if f is None:
        return {}
    s = float(op.attrs.get("scale", 1.0))
    b = float(op.attrs.get("bias", 0.0))
    if s < 0:
        return {}
    bound = None
    if f.bound is not None and b >= 0:
        # v <= bound-1 and s >= 0 make (bound-1)*s + b an upper
        # bound regardless of v's sign; b < 0 could go negative, so
        # the bound AND the nonneg claim are dropped together below
        bound = int(math.floor((f.bound - 1) * s + b)) + 1
    plain = s == 1.0 and b == 0.0
    return _all_outs(op, f.with_step(
        f"scale(x{s}+{b})", bound=bound,
        indicator=f.indicator and plain,
        onehot=f.onehot and plain,
        distinct=f.distinct and s > 0,
        nonneg=f.nonneg and b >= 0,
        const=f.const))


def _ew_facts(op, prov_of):
    fx = prov_of(_in(op, "X") or "")
    fy = prov_of(_in(op, "Y") or "")
    return fx, fy


@_family("elementwise-add", ("elementwise_add",))
def _ew_add(op, prov_of, shape_of):
    fx, fy = _ew_facts(op, prov_of)
    if fx is None or fy is None:
        return {}
    bound = None
    if fx.bound is not None and fy.bound is not None:
        bound = fx.bound + fy.bound - 1
    return _all_outs(op, ProvFact(
        tuple(sorted(set(fx.tags) | set(fy.tags))),
        fx.const and fy.const, bound=bound,
        nonneg=fx.nonneg and fy.nonneg,
        chain=_chain(fx.chain or fy.chain, op.type)))


@_family("elementwise-sub", ("elementwise_sub",))
def _ew_sub(op, prov_of, shape_of):
    fx, fy = _ew_facts(op, prov_of)
    if fx is None or fy is None:
        return {}
    # v1 - v2 <= v1 < bound(v1) ONLY when v2 is provably >= 0 — a
    # possibly-negative subtrahend inflates the value past any
    # certified bound, so the bound is dropped with it. The result
    # itself can go negative (nonneg=False), except the
    # (const 1) - indicator mask idiom, which stays a 0/1 indicator
    # — but a COMPLEMENT carries NO source tags: 1-active is the
    # idle mask, not the active mask, and letting it keep the
    # lane_active tag would pass an INVERTED gate through PTA190's
    # gate proof (idle lanes writing, active lanes frozen — the
    # exact corruption the gate exists to stop).
    ind = fx.const and fx.bound == 2 and fy.indicator
    return _all_outs(op, ProvFact(
        () if ind else tuple(sorted(set(fx.tags) | set(fy.tags))),
        fx.const and fy.const, indicator=ind,
        bound=fx.bound if fy.nonneg else None,
        nonneg=ind,
        chain=_chain(fx.chain or fy.chain, op.type)))


@_family("elementwise-mul", ("elementwise_mul",))
def _ew_mul(op, prov_of, shape_of):
    fx, fy = _ew_facts(op, prov_of)
    if fx is None or fy is None:
        return {}
    tags = tuple(sorted(set(fx.tags) | set(fy.tags)))
    chain = _chain(fx.chain or fy.chain, op.type)
    for a, b in ((fx, fy), (fy, fx)):
        if a.indicator and not b.indicator:
            # gating/selection: values are b's entries or 0 —
            # b's bound and tags survive; a ONE-HOT selector makes
            # the product summable without losing the bound (the
            # selector's block extent rides along for the reduce's
            # containment check). 0 is only inside b's bound on the
            # non-negative domain.
            sel = a.onehot and b.nonneg
            return _all_outs(op, ProvFact(
                tags, a.const and b.const,
                bound=b.bound if b.nonneg else None,
                selection=sel, nonneg=b.nonneg,
                oh_tail=a.oh_tail if sel else 0, chain=chain))
    if fx.indicator and fy.indicator:
        # nonzeros of the product are a subset of EACH operand's, so
        # any one-hot claim survives — keep the stronger (larger)
        # block
        tail = max(fx.oh_tail if fx.onehot else 0,
                   fy.oh_tail if fy.onehot else 0)
        return _all_outs(op, ProvFact(
            tags, fx.const and fy.const, indicator=True,
            onehot=tail > 0, bound=2, oh_tail=tail, chain=chain))
    bound = None
    if fx.bound is not None and fy.bound is not None \
            and fx.nonneg and fy.nonneg:
        # (b1-1)*(b2-1)+1 needs both operands >= 0 (two negatives
        # multiply to an arbitrarily large positive)
        bound = (fx.bound - 1) * (fy.bound - 1) + 1
    return _all_outs(op, ProvFact(
        tags, fx.const and fy.const, bound=bound,
        nonneg=fx.nonneg and fy.nonneg, chain=chain))


@_family("elementwise-minmax", ("elementwise_min",
                                "elementwise_max"))
def _ew_minmax(op, prov_of, shape_of):
    fx, fy = _ew_facts(op, prov_of)
    if fx is None or fy is None:
        return {}
    bounds = [b for b in (fx.bound, fy.bound) if b is not None]
    if op.type == "elementwise_min":
        bound = min(bounds) if bounds else None
        nonneg = fx.nonneg and fy.nonneg
    else:
        bound = max(bounds) if len(bounds) == 2 else None
        nonneg = fx.nonneg or fy.nonneg
    return _all_outs(op, ProvFact(
        tuple(sorted(set(fx.tags) | set(fy.tags))),
        fx.const and fy.const,
        indicator=fx.indicator and fy.indicator, bound=bound,
        nonneg=nonneg,
        chain=_chain(fx.chain or fy.chain, op.type)))


# --- indicator mints --------------------------------------------------------
@_family("compare", (
        "equal", "not_equal", "greater_than", "greater_equal",
        "less_than", "less_equal", "logical_and", "logical_or",
        "logical_xor", "logical_not"))
def _compare(op, prov_of, shape_of):
    fx, fy = _ew_facts(op, prov_of)
    onehot = False
    if op.type == "equal":
        # equal(distinct-constant 1-D axis, BROADCAST value) matches
        # at most one entry along the constant's axis — the one-hot
        # mint every paged page/offset selection is built from. The
        # broadcast shape is part of the proof: the other operand
        # must be scalar-per-row (trailing dim 1 / scalar), or a
        # same-length vector (equal(range(N), ids[N]) can match
        # EVERY position) would be falsely certified one-hot.
        for a_slot, b_slot, fa in (("X", "Y", fx), ("Y", "X", fy)):
            if fa is None or not (fa.const and fa.distinct):
                continue
            sa = shape_of(_in(op, a_slot) or "")
            sb = shape_of(_in(op, b_slot) or "")
            if sa is not None and len(sa) == 1 \
                    and sb is not None \
                    and (len(sb) == 0 or sb[-1] == 1):
                onehot = True
                break
    return _all_outs(op, ProvFact(
        const=all(f is not None and f.const for f in (fx, fy)),
        indicator=True, onehot=onehot, bound=2,
        oh_tail=1 if onehot else 0,
        chain=(op.type,)))


@_family("one-hot", ("one_hot",))
def _one_hot(op, prov_of, shape_of):
    return _all_outs(op, ProvFact(indicator=True, onehot=True,
                                  bound=2, oh_tail=1,
                                  chain=("one_hot",)))


# --- contractions -----------------------------------------------------------
def _tail_reduced(op, shape_of, oh_tail):
    """(contained, n) — whether the reduce's dims all lie INSIDE the
    one-hot fact's trailing block (the last ``oh_tail`` axes), and
    how many of them do. The <=1-nonzero claim only survives a
    reduce that stays inside the block: reducing a leading (row)
    axis sums one-hots from DIFFERENT rows (the admission mask
    `reduce_sum(oh, dim=0)` counts up to A) and the claim breaks."""
    dims = op.attrs.get("dim")
    if dims is None:
        return False, 0              # full reduce: rows included
    if isinstance(dims, int):
        dims = [dims]
    try:
        dims = [int(d) for d in dims]
    except (TypeError, ValueError):
        return False, 0
    shape = shape_of(_in(op, "X") or "")
    if shape is None:
        return False, 0              # rank unknown: unprovable
    rank = len(shape)
    norm = [d + rank if d < 0 else d for d in dims]
    ok = all(rank - oh_tail <= d < rank for d in norm) \
        and 0 < oh_tail <= rank
    return ok, len(set(norm))


@_family("reduce", ("reduce_sum", "reduce_max", "reduce_min",
                    "reduce_mean"))
def _reduce(op, prov_of, shape_of):
    f = prov_of(_in(op, "X") or "")
    if f is None:
        return {}
    if op.type in ("reduce_max", "reduce_min"):
        # per-ELEMENT properties (bound, indicator, tags) survive a
        # max/min regardless of axes; the per-row ONE-HOT block
        # survives only a reduce INSIDE it (a dim=0 reduce_max of
        # an [A, rows] one-hot is an any-mask with up to A nonzeros)
        keep, n = (False, 0) if not f.onehot else \
            _tail_reduced(op, shape_of, f.oh_tail)
        return _all_outs(op, f.with_step(
            op.type, selection=False, distinct=False,
            onehot=f.onehot and keep,
            oh_tail=f.oh_tail - n if (f.onehot and keep) else 0))
    if op.type == "reduce_mean":
        return _all_outs(op, f.with_step(op.type, selection=False,
                                         distinct=False,
                                         onehot=False, oh_tail=0,
                                         indicator=False))
    if f.selection:
        keep, _n = _tail_reduced(op, shape_of, f.oh_tail)
        if keep:
            # sum over a bounded x one-hot product, inside the
            # selector's trailing block: picks at most one entry —
            # the selected operand's tags and bound survive
            return _all_outs(op, f.with_step(
                "reduce_sum[selection]", selection=False,
                onehot=False, oh_tail=0, indicator=False,
                distinct=False))
    if f.onehot:
        keep, n = _tail_reduced(op, shape_of, f.oh_tail)
        if keep:
            # summing groups WITHIN a per-row one-hot block stays
            # 0/1-valued; a fully-reduced block degrades to a plain
            # per-row indicator
            tail = f.oh_tail - n
            return _all_outs(op, f.with_step(
                "reduce_sum[one-hot]", distinct=False,
                onehot=tail > 0, oh_tail=tail))
    if f.const:
        return _all_outs(op, ProvFact(const=True,
                                      chain=_chain(f.chain, op.type)))
    return {}


@_family("matmul", ("matmul", "mul"))
def _matmul(op, prov_of, shape_of):
    fx, fy = _ew_facts(op, prov_of)
    # a one-hot X operand makes the contraction a pure selection of
    # Y's rows (reduce_sum(onehot * vals) in matmul clothing): X's
    # per-row one-hot block must span EXACTLY the contracted (last)
    # axis — oh_tail == 1. Y-side one-hots do NOT qualify (Y's
    # per-row one-hot is along the NON-contracted axis, so one
    # column of Y can hold many nonzeros), nor does a transposed X.
    if fx is not None and fx.onehot and fx.oh_tail == 1 \
            and fy is not None \
            and not op.attrs.get("transpose_X") \
            and not op.attrs.get("transpose_x"):
        return _all_outs(op, fy.with_step(
            f"{op.type}[one-hot-select]",
            bound=fy.bound if fy.nonneg else None,
            selection=False, onehot=False, oh_tail=0,
            indicator=False, distinct=False))
    if fx is not None and fy is not None and fx.const and fy.const:
        return _all_outs(op, ProvFact(
            const=True, chain=_chain(fx.chain or fy.chain, op.type)))
    return {}
