"""The liveness domain: capacity feasibility, release obligations,
and While-variant proofs.

Three provers, one module (the checkers PTA200/201/202 in
checkers.py are thin wrappers over these):

* **Admission-capacity feasibility** (PTA200): a declarative resource
  model of the host serving protocol. Each acquire site the ownership
  domain already names (absint's tag table) draws from one of two
  pools — ``HostBlockPool`` blocks or ``PromptPrefixCache`` entries —
  and the worst-case steady-state demand per serving configuration is
  arithmetic over the bundle's static shape: ``n_slots`` lanes times
  ``pages(max_out_len)`` blocks each, one entry per concurrently-live
  distinct prompt, PLUS one pinned entry per open chat session per
  DISTINCT session prompt (sessions retain their entry ref for their
  whole lifetime — ``_harvest_session_locked`` transfers, never
  releases). Feasible means admission can always eventually make
  progress; infeasible comes with a concrete witness. The predicate
  is validated against the exhaustive explorer in
  analysis/protomodel.py (tests/test_protomodel.py runs the grid), so
  the static claim inherits proof-up-to-bound strength without
  enumerating states at lint time.

* **Release-on-every-exit-path** (PTA201): every acquire obligation
  (an ``AcquireContract`` registered beside the ownership tag it
  attaches to) must have a registered release SITE on every declared
  protocol exit path. The sites register from the code that
  implements them (inference/serving.py module scope), so the ledger
  names real methods; a tag a program exercises with no contract, or
  a declared exit with no site, is an unproven obligation.

* **While-variant progress** (PTA202): a While loop terminates when
  it has a sound variant — a monotone step counter (an ``increment``
  op with positive step in the condition's backward slice) bounded by
  a loop-invariant limit (a data feed or trace-time constant). The
  serve/burst Whiles' second disjunct (the ``lane_active_mask``
  divergence mark on the condition's producer) rides a NAMED
  monotone-mask assumption: active lanes only ever retire within a
  burst, so the mask term is monotone non-increasing and the counter
  term alone bounds the loop.

Reference counterpart: none — the reference's liveness story is
runtime watchdogs and PADDLE_ENFORCE timeouts (reference
framework/operator.cc enforcement tier); proving admission progress
and release coverage statically is the shared-pool serving-era
capability this layer adds on top of the PTA190 ownership proofs.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from . import absint
from .dataflow import analyze_block, iter_blocks, iter_ops

__all__ = [
    "CapacityCheck", "session_feasibility", "bundle_capacity_checks",
    "bundle_liveness_facts", "obligation_ledger",
    "unproven_obligations", "WhileVariant", "while_variants",
    "stable_liveness_facts",
]


# ---------------------------------------------------------------------------
# PTA200: the capacity model.
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class CapacityCheck:
    """One resource-pool feasibility verdict: worst-case steady-state
    ``demand`` against static ``supply``, with a concrete ``witness``
    sentence when infeasible. Reference counterpart: none (module
    docstring)."""
    resource: str
    demand: int
    supply: int
    feasible: bool
    witness: Optional[str] = None

    def describe(self) -> str:
        verdict = "feasible" if self.feasible else "INFEASIBLE"
        return f"{self.resource}: demand {self.demand} <= supply " \
               f"{self.supply} [{verdict}]" if self.feasible else \
               f"{self.resource}: demand {self.demand} > supply " \
               f"{self.supply} [{verdict}]"


def session_feasibility(n_prompt_entries: int, distinct_prompts: int,
                        sessions_close: bool = False,
                        cold_traffic: bool = False) -> CapacityCheck:
    """THE session-pinning capacity predicate (the one source of
    truth — the serving preflight and PTA200 both call this; the
    protomodel ``session_protocol`` explorer is its oracle). A chat
    session PINS one ``PromptPrefixCache`` entry per distinct prompt
    for its whole lifetime; with sessions that never close, steady-
    state demand is the distinct-session-prompt count (plus one
    churnable entry when non-session traffic shares the cache), and
    admission wedges forever the moment demand exceeds the entry
    pool — no eviction can help because every entry is pinned.
    Reference counterpart: none (module docstring)."""
    demand = int(distinct_prompts) + (1 if cold_traffic else 0)
    supply = int(n_prompt_entries)
    feasible = bool(sessions_close) or demand <= supply
    witness = None
    if not feasible:
        witness = (
            f"session-pinning admission deadlock: {distinct_prompts} "
            f"distinct session prompts"
            + (" + 1 churn entry for non-session traffic"
               if cold_traffic else "")
            + f" each pin a PromptPrefixCache entry for the session "
              f"lifetime, but n_prompt_entries={n_prompt_entries}; "
              f"once {n_prompt_entries} sessions are admitted every "
              f"entry is pinned (refcount>0, unevictable) and every "
              f"later admission waits forever (protomodel "
              f"session_protocol finds the minimal wedge trace)")
    return CapacityCheck("PromptPrefixCache", demand, supply,
                         feasible, witness)


def bundle_capacity_checks(bundle) -> List[CapacityCheck]:
    """Worst-case steady-state capacity checks for one decode bundle
    (duck-typed on n_slots/max_out_len/cache; paged layouts only —
    dense bundles hold no pool resources). Block demand assumes NO
    radix sharing (sharing only lowers it); entry demand assumes
    every live lane holds a distinct prompt plus whatever the
    bundle's declared ``workload`` dict pins through sessions.
    Reference counterpart: none (module docstring)."""
    cache = getattr(bundle, "cache", None)
    if cache is None or getattr(cache, "layout", "dense") != "paged":
        return []
    checks: List[CapacityCheck] = []
    n_slots = int(getattr(bundle, "n_slots", 0))
    max_out = int(getattr(bundle, "max_out_len", 0))
    pages = cache.pages(max_out)
    demand = n_slots * pages
    feasible = demand <= cache.n_blocks
    checks.append(CapacityCheck(
        "HostBlockPool", demand, cache.n_blocks, feasible,
        None if feasible else (
            f"{n_slots} lanes x {pages} pages "
            f"(max_out_len={max_out} / block_size="
            f"{cache.block_size}) = {demand} blocks exceed "
            f"n_blocks={cache.n_blocks}: a full admission round "
            f"cannot allocate its write-reachable chains and decode "
            f"stalls behind preemption forever")))
    workload = getattr(bundle, "workload", None)
    if isinstance(workload, dict) \
            and "distinct_session_prompts" in workload:
        checks.append(session_feasibility(
            cache.n_prompt_entries,
            int(workload["distinct_session_prompts"]),
            sessions_close=bool(workload.get("sessions_close",
                                             False)),
            cold_traffic=bool(workload.get("cold_traffic", False))))
    else:
        # no declared session workload: lanes churn entries, so the
        # steady-state entry demand is one fresh entry per admission
        # wave (entries release on retirement) — feasible whenever
        # the cache has any entry at all.
        demand = 1 if n_slots else 0
        checks.append(CapacityCheck(
            "PromptPrefixCache", demand, cache.n_prompt_entries,
            demand <= cache.n_prompt_entries,
            None if demand <= cache.n_prompt_entries else (
                f"paged serving with n_prompt_entries="
                f"{cache.n_prompt_entries} cannot admit even one "
                f"prompt")))
    return checks


def bundle_liveness_facts(bundle) -> Dict[str, str]:
    """Stable per-bundle capacity facts for the baseline's
    ``liveness_facts`` section (keys are resource pools — stable by
    construction). Chunked bundles also record the two-tier schedule
    bound: a decode tick never waits longer than ONE C-token chunk
    phase, so prefill progress cannot starve decode progress.
    Reference counterpart: none (module docstring)."""
    facts: Dict[str, str] = {}
    for chk in bundle_capacity_checks(bundle):
        facts[f"@capacity:{chk.resource}"] = chk.describe()
    cache = getattr(bundle, "cache", None)
    if cache is not None and getattr(cache, "chunk_tokens", 0):
        facts["@decode-wait"] = (
            f"two-tier schedule: decode tick waits <= one "
            f"chunk_tokens={cache.chunk_tokens} prefill phase")
    return facts


# ---------------------------------------------------------------------------
# PTA201: the obligation ledger.
# ---------------------------------------------------------------------------
_PROTOCOL_SITES_LOADED = False


def _ensure_protocol_sites() -> None:
    """Import the serving layer so its module-scope
    ``register_release_site`` calls populate the registry. Lazy and
    memoized: the analysis package stays importable (and IR-level)
    without the inference stack; only the ledger needs the real site
    table, and an import failure surfaces as loudly-missing sites,
    never a silent pass."""
    global _PROTOCOL_SITES_LOADED
    if _PROTOCOL_SITES_LOADED:
        return
    try:
        from ..inference import serving  # noqa: F401
    except Exception:  # pragma: no cover - loud downstream anyway
        return  # don't latch: retry on the next ledger build
    _PROTOCOL_SITES_LOADED = True


def obligation_ledger(facts) -> dict:
    """The per-program acquire/release obligation ledger (mirrors
    ``ProgramFacts.ownership_ledger``): which contracts the program's
    pool accesses actually exercise (via their index-provenance
    tags), which exit paths each is proven on (registered release
    sites, counted), and which obligations remain unproven — a tag
    with no contract, or a declared exit with no site. The CLI's
    --json liveness surface and the CI gate's artifact both read
    this. Reference counterpart: none (module docstring)."""
    _ensure_protocol_sites()
    sources = absint.pool_index_sources()
    contracts = absint.acquire_contracts()
    sites = absint.release_sites()
    used: Dict[str, int] = {}
    for acc in facts.pool_accesses:
        fact = acc.index_fact
        if fact is None:
            continue
        for t in fact.tags:
            src = sources.get(t)
            if src is None or src.typestate == absint.TS_GATE:
                continue
            used[t] = used.get(t, 0) + 1
    obligations: Dict[str, dict] = {}
    unproven: List[str] = []
    for tag in sorted(used):
        contract = contracts.get(tag)
        if contract is None:
            unproven.append(
                f"{tag}: no acquire/release contract registered "
                f"(absint.register_acquire_release)")
            continue
        exits: Dict[str, List[str]] = {}
        for exit_path in contract.exits:
            got = sites.get((tag, exit_path), [])
            exits[exit_path] = list(got)
            if not got:
                unproven.append(
                    f"{tag}: declared exit path {exit_path!r} has "
                    f"no registered release site")
        obligations[tag] = {
            "resource": contract.resource,
            "acquire": contract.acquire,
            "release": contract.release,
            "sites": used[tag],
            "exits": exits,
        }
    return {"obligations": obligations, "unproven": unproven,
            "proven": sum(1 for tag in obligations
                          if not any(u.startswith(f"{tag}:")
                                     for u in unproven))}


def unproven_obligations(facts) -> List[str]:
    """Just the unproven list (the PTA201 error surface). Reference
    counterpart: none (module docstring)."""
    return obligation_ledger(facts)["unproven"]


# ---------------------------------------------------------------------------
# PTA202: While variants.
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class WhileVariant:
    """One While loop's progress verdict. ``counter`` is the name the
    in-body ``increment`` op steps; ``bound_terms`` are the FED
    loop-invariant terminals of the condition's backward slice
    (feed names only — consts/outer temps bound the variant but
    carry build-order-dependent names); ``kind`` is "serve" when the
    condition producer carries the ``lane_active_mask`` divergence
    mark (the burst-exit disjunct), else "plain"; ``assumption``
    names the monotone-mask fact serve variants additionally rest
    on. Reference counterpart: none (module docstring)."""
    site_key: str
    anchor: str
    proven: bool
    counter: Optional[str]
    bound_terms: Tuple[str, ...]
    kind: str
    assumption: Optional[str] = None
    detail: Optional[str] = None
    site: object = None             # the OpSite, for diag anchoring

    def describe(self) -> str:
        # counter names are auto-generated temps (process-global
        # build order) — the STABLE description records presence +
        # the fed bound names only
        if self.proven:
            desc = (f"variant[counter bound="
                    f"{','.join(self.bound_terms) or 'const/outer'}]")
            if self.assumption:
                desc += f" +{self.assumption}"
            return desc
        return f"UNPROVEN[{self.detail}]"


_MONOTONE_MASK_ASSUMPTION = "monotone-lane_active_mask"


def _var_of(name: str, name_to_var: dict):
    return name_to_var.get(name)


def while_variants(program) -> List[WhileVariant]:
    """Prove (or fail to prove) a termination variant for every While
    in ``program``. The slice walks backward from the body's writer
    of the Condition var through in-body writers; terminals classify
    as feed (data var), const (``fill_constant`` producer in the
    body), state (persistable), or outer (parent-block value — loop-
    invariant by construction since the body cannot write it). A
    variant is proven when the slice contains a positive-step
    ``increment`` AND at least one feed/const/outer bound terminal.
    Reference counterpart: none (module docstring)."""
    name_to_var: dict = {}
    for blk, _ in iter_blocks(program):
        for name, var in blk.vars.items():
            name_to_var.setdefault(name, var)
    out: List[WhileVariant] = []
    n = 0
    for site in iter_ops(program):
        op = site.op
        if op.type != "while":
            continue
        key = f"@while#{n}"
        n += 1
        cond_name = op.inputs.get("Condition", [None])[0]
        body = op.attr("sub_block")
        if cond_name is None or body is None:
            out.append(WhileVariant(
                key, site.anchor(), False, None, (), "plain",
                detail="no Condition input or sub_block",
                site=site))
            continue
        df = analyze_block(body)
        writers = df.writers
        if cond_name not in writers:
            out.append(WhileVariant(
                key, site.anchor(), False, None, (), "plain",
                detail=f"body never recomputes condition "
                       f"{cond_name!r} — the loop can only spin",
                site=site))
            continue
        cond_writer = body.ops[writers[cond_name][-1]]
        kind = "plain"
        assumption = None
        if cond_writer.attrs.get(absint.DIVERGENCE_ATTR) \
                == "lane_active_mask":
            kind = "serve"
            assumption = _MONOTONE_MASK_ASSUMPTION
        # backward slice through in-body writers
        counter = None
        bound_terms: List[str] = []
        has_bound = False
        seen_names: set = set()
        work = [nm for nm in cond_writer.input_arg_names]
        visited_ops = {id(cond_writer)}
        while work:
            nm = work.pop()
            if nm in seen_names:
                continue
            seen_names.add(nm)
            idxs = writers.get(nm)
            if not idxs:
                var = _var_of(nm, name_to_var)
                if var is not None and var.is_data:
                    # only FED names land in bound_terms: feed names
                    # are author-chosen and stable across builds,
                    # unlike auto-generated temps in parent blocks
                    bound_terms.append(nm)
                    has_bound = True
                elif var is not None and var.persistable:
                    # state: the step may rewrite it between runs, so
                    # it is not a loop-invariant bound (and param
                    # names would drown the description in noise)
                    pass
                else:
                    # parent-block value: loop-invariant (the body
                    # cannot write it), so it bounds the variant —
                    # but its name is usually a temp; record presence
                    # only
                    has_bound = True
                continue
            producer = body.ops[idxs[-1]]
            if producer.type == "fill_constant":
                has_bound = True
                continue
            if producer.type == "increment" \
                    and float(producer.attr("step", 1.0)) > 0:
                counter = nm
                continue
            if id(producer) not in visited_ops:
                visited_ops.add(id(producer))
                work.extend(producer.input_arg_names)
        proven = counter is not None and has_bound
        detail = None
        if not proven:
            missing = []
            if counter is None:
                missing.append("no increment-driven counter in the "
                               "condition slice")
            if not has_bound:
                missing.append("no loop-invariant bound terminal "
                               "(feed/const/outer)")
            detail = "; ".join(missing)
        out.append(WhileVariant(
            key, site.anchor(), proven, counter,
            tuple(sorted(bound_terms)), kind, assumption, detail,
            site=site))
    return out


def stable_liveness_facts(facts) -> Dict[str, str]:
    """Per-program liveness summary over STABLE names for the CI
    baseline's drift-gated ``liveness_facts`` section: one entry per
    While (ordinal keys — While count and order are build-determined,
    not process-global), plus an ``@obligations`` roll-up naming the
    exercised contracts (mirrors ``stable_ownership_facts``'s
    ``@assumptions`` convention). Reference counterpart: none
    (module docstring)."""
    out: Dict[str, str] = {}
    for v in while_variants(facts.program):
        desc = v.describe()
        if v.kind == "serve":
            desc = f"serve {desc}"
        out[v.site_key] = desc
    ledger = obligation_ledger(facts)
    if ledger["obligations"]:
        bits = []
        for tag, entry in sorted(ledger["obligations"].items()):
            n_exits = sum(1 for s in entry["exits"].values() if s)
            bits.append(f"{tag}->{entry['release']}"
                        f"[{n_exits}/{len(entry['exits'])} exits]")
        out["@obligations"] = ",".join(bits)
    if ledger["unproven"]:
        out["@unproven"] = ";".join(sorted(ledger["unproven"]))
    return out
