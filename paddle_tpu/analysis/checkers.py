"""Checker suite: static verification passes over the Program IR.

Reference counterparts: paddle/fluid/framework/op_desc.cc (attr/shape
checks at OpDesc build), operator.cc RunImpl enforcement, and the
transpiler-era program validators. The whole-block-jit Executor has no
per-op hook, so invalid programs here historically failed DEEP inside
a jax trace — or deadlocked a real TPU (CLAUDE.md session learnings).
Every checker below is grounded in one of those incidents and carries
a stable diagnostic code so tests/docs can reference the class:

  PTA001  uninitialized read            (go/_launch_go_ops bug class)
  PTA002  multiple writers              (ambiguous recompute/go capture)
  PTA003  dead op                       (build waste; XLA would DCE)
  PTA004  go-capture hazard             (late writer / host producer)
  PTA010  collective in divergent branch (r5 pp deadlock trap)
  PTA011  maybe-collective in branch    (scope-dependent lowering)
  PTA020  while-carry dtype promotion   (increment int->float trap)
  PTA030  duplicate uid on sampling ops (fwd/bwd noise divergence)
  PTA031  clone dropped/mutated uid     (Program.clone contract)
  PTA040  recompute clone not barrier-rooted (XLA CSE undoes remat)
  PTA050  auto-generated param names    (cross-build sharing fragility)
  PTA051  cross-program shared-name conflict
  PTA060  @SEQ_LEN companion mismatch   (static-batch probe trap)
  PTA070  host_effect flag missing      (run_steps scan correctness)
  PTA080  unregistered op type
  PTA090  write-only persistable not carry-declarable (r6 scan-carry
          trap: run_steps/prepare(steps=K) seed it with zeros)
  PTA100  cross-model param-name collision (co-resident serving
          runtime models aliasing/clobbering one scope's weights)
  PTA110  shared-pool write not provably lane-exclusive (paged KV
          block pools: aliased scatter = silent cross-request KV
          corruption)
  PTA120  speculative advance bound unprovable (spec_accept shape/
          attr disagreement: the counter-advance <= k+1 clamp and
          the accepted-prefix scatter's room clip are only sound
          when the declared k/max_len match the wired tensors)
  PTA130  collective under divergent control flow, PROVEN (absint
          guard contexts: subsumes PTA010/011, which remain as its
          fast-path corroboration — every diagnostic carries the
          per-guard divergence classification and source chain)
  PTA131  replicated value differentiated / sharded value consumed
          inside a divergent context (the r5 trap family: the grad
          transpose of an implicit replicated->varying cast is a
          psum, and an auto-axis sharding annotation reaching a
          divergent site invites a GSPMD-inserted collective)
  PTA140  declared shape/dtype clobbered by producer inference (the
          r10 'shape inference CLOBBERS a declared persistable'
          class; generalizes PTA020's int->float promotion beyond
          the `increment` special case)
  PTA150  decode-bundle contract (check_bundle: all serve/admission/
          step specializations of one DecodeStepBundle must agree on
          cache geometry, seed derivation, and counter presence)
  PTA160  sharding contradiction / implicit reshard (the sharding
          domain: consumers demanding incompatible ShardSpecs, or a
          GSPMD-forced reshard landing inside a serve-While body —
          the r5 'dp on the pre-reshape dim' trap, proven from the
          propagated specs instead of pattern-matched)
  PTA161  collective-order agreement (symbolic enumeration of the
          collective sequence each mesh coordinate observes through
          divergent guards, over BOTH literal collective ops and the
          sharding-implied psum/allgather/reshard events; ERROR when
          two coordinates can disagree — the 1F1B x tp vocab-psum
          rejection becomes a corollary of this proof)
  PTA170  per-device memory budget (the static planner
          analysis/memplan.py: persistable/feed/temp bytes under the
          propagated specs vs an opt-in per-program budget)
  PTA180  device-telemetry counter contract (@TEL-marked counters —
          observability/devtel.py — must be int64, concretely
          declared, persistable, and read-modify-write wherever
          written: the PTA020/PTA090 lessons applied to the decode
          flight-data subsystem; a drifted counter poisons every
          stats window with no downstream error)
  PTA190  pool-access provenance + in-bounds (the ownership domain,
          absint ProvFact: every index reaching a @POOL read/write
          must chain to a registered host-owned source or a
          trace-time constant, block-table writes must be gated by
          the lane-active mask, and the index bound must fit the
          indexed axis — unknown provenance is ERROR with the chain
          printed)
  PTA191  lane-exclusive write PROVEN (given the host allocator's
          disjoint-allocation invariant as a NAMED assumption, the
          provenance proof shows distinct lanes' writes hit disjoint
          rows — subsumes PTA110's syntactic declaration the way
          PTA130 subsumed PTA010: twin-dedupe at prover-covered
          sites, the exclusive_via declaration survives as the
          assumption's name and must AGREE with the proven chain)
  PTA192  read-only-while-shared (writes are only legal in the
          exclusive typestate of the free→exclusive→shared→freed
          block lifetime lattice: an index whose provenance chains
          to a REFCOUNTED source — prompt_entry_ref — certifies
          reads only; a write through it is the COW violation the
          radix/beam prefix-sharing work must never ship)
  PTA200  admission-capacity feasibility (the liveness domain,
          analysis/liveness.py: worst-case steady-state resource
          demand per serving configuration vs the static pools —
          lane block chains vs HostBlockPool, pinned session
          prompts vs PromptPrefixCache entries; an infeasible
          config gets a concrete deadlock witness, validated
          against the exhaustive protomodel explorer)
  PTA201  release-on-every-exit-path (every acquire obligation an
          ownership tag creates — absint.register_acquire_release —
          must have a registered release SITE on every declared
          protocol exit path: retirement, preemption, abort,
          invalidate, session/server close, handoff; an
          undischarged path is a leak nobody is maintaining)
  PTA202  serve-While progress (every While must carry a SOUND
          variant: an increment-driven counter in its condition's
          backward slice bounded by a loop-invariant feed/const;
          serve/burst Whiles additionally rest on the NAMED
          monotone-lane_active_mask assumption for their burst-exit
          disjunct)

Severities: "error" = the program is wrong (strict mode raises),
"warning" = almost certainly a bug but a legal feed/scope could save
it, "info" = hygiene finding. `run_checks(program)` runs everything;
per-site suppressions ride the ``_pta_suppress=("PTA0xx", "reason")``
op attr (counted, surfaced in the CLI's --json and the CI baseline).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional

from ..core.program import Block, Operator, Program
from ..core.registry import (EMPTY_VAR, get_op_info, is_registered,
                             kernel_bridges_host)
from .dataflow import (BlockDataflow, OpSite, analyze_block,
                       block_entry_names, iter_blocks, iter_ops,
                       iter_sub_blocks)

__all__ = ["Diagnostic", "Checker", "register_checker", "run_checks",
           "check_registry", "check_shared_params", "check_clone_uids",
           "check_cross_model_collision", "check_bundle",
           "registered_checkers", "format_diagnostics",
           "ERROR", "WARNING", "INFO", "SUPPRESS_ATTR"]

ERROR, WARNING, INFO = "error", "warning", "info"

# per-site diagnostic suppression: an op carrying
# _pta_suppress=("PTA0xx", "reason") — or a list/tuple of such pairs —
# silences diagnostics of that code ANCHORED AT that op. Suppressions
# are counted and surfaced (CLI --json `suppressed`, CI baseline), so
# they are reviewable debt, not disappearances.
SUPPRESS_ATTR = "_pta_suppress"

# ops the Executor skips at trace time (core/executor.py _SKIP_OP_TYPES
# plus the feed/fetch placeholders that are never registered)
_PLUMBING = ("feed", "fetch")

# cross-process / cross-device collective ops (ops/dist_ops.py): their
# host-bridge (ordered io_callback) or psum sequencing must be
# IDENTICAL on every participant — a divergent lax.cond/while means
# participants disagree on whether the collective runs at all
DIST_OP_TYPES = frozenset({
    "send", "recv", "send_barrier", "fetch_barrier", "prefetch",
    "prefetch_grad", "checkpoint_notify", "allreduce",
    "listen_and_serv", "gen_nccl_id",
})

# ops whose kernels lower through shard_map / with_sharding_constraint
# when a parallel scope (context/expert parallel) is active — inside a
# divergent branch GSPMD may then plant a collective in the branch
# body (the r6 1F1B x tp generalized trap)
SCOPE_COLLECTIVE_OP_TYPES = frozenset({
    "attention", "attention_block", "switch_moe",
})

# container op type -> whether its sub-blocks trace as DIVERGENT
# control flow (lax.cond / lax.while_loop): different devices can take
# different paths, so a collective inside deadlocks
DIVERGENT_CONTAINERS = frozenset({
    "conditional_block", "run_block_if", "ifelse", "while",
})

_AUTO_PARAM_RE = re.compile(r"_\d+\.[wb]_\d+$")

RECOMP_MARK = "@RECOMP"
BARRIER_MARK = "@BAR"


@dataclass(frozen=True)
class Diagnostic:
    """One structured finding (reference: the EnforceNotMet message the
    C++ validators would have raised, made machine-readable)."""
    code: str
    severity: str
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None

    def format(self) -> str:
        where = f"block {self.block_idx}"
        if self.op_idx is not None:
            where += f" op {self.op_idx}"
        if self.op_type:
            where += f" ({self.op_type})"
        out = f"{self.code} [{self.severity}] {where}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


def _diag_at(code, severity, site: OpSite, message, var=None,
             hint=None) -> Diagnostic:
    return Diagnostic(code, severity, message, block_idx=site.block_idx,
                      op_idx=site.op_idx, op_type=site.op.type, var=var,
                      hint=hint)


@dataclass
class Checker:
    code: str
    name: str
    fn: Callable[[Program], Iterable[Diagnostic]]
    doc: str = ""


_CHECKERS: Dict[str, Checker] = {}


def register_checker(code: str, name: str, doc: str = ""):
    """Decorator registering `fn(program) -> iterable of Diagnostic`
    under a stable PTA code (mirrors core/registry.register_op)."""

    def deco(fn):
        _CHECKERS[code] = Checker(code, name, fn, doc or fn.__doc__ or "")
        return fn

    return deco


def registered_checkers() -> List[Checker]:
    return [_CHECKERS[c] for c in sorted(_CHECKERS)]


def _normalize_suppressions(raw):
    """Accept ("PTA0xx", "reason") or a list/tuple of such pairs;
    return [(code, reason)] or None for a malformed attr."""
    if isinstance(raw, (list, tuple)) and len(raw) == 2 and \
            all(isinstance(x, str) for x in raw):
        raw = [raw]
    if not isinstance(raw, (list, tuple)):
        return None
    out = []
    for entry in raw:
        if not (isinstance(entry, (list, tuple)) and len(entry) == 2
                and all(isinstance(x, str) for x in entry)
                and re.fullmatch(r"PTA\d{3}", entry[0])):
            return None
        out.append((entry[0], entry[1]))
    return out


def _collect_suppressions(program: Program):
    """(block_idx, op_idx, code) -> reason, plus malformed-attr
    diagnostics (a suppression that silently failed to parse would be
    a suppression that silently does nothing)."""
    sup: Dict[tuple, str] = {}
    malformed: List[Diagnostic] = []
    for site in iter_ops(program):
        raw = site.op.attrs.get(SUPPRESS_ATTR)
        if raw is None:
            continue
        entries = _normalize_suppressions(raw)
        if entries is None:
            malformed.append(_diag_at(
                "PTA199", WARNING, site,
                f"malformed {SUPPRESS_ATTR} attr {raw!r}; expected "
                f"(\"PTA0xx\", \"reason\") or a list of such pairs "
                f"— the suppression is IGNORED",
                hint="fix the attr; nothing is suppressed until it "
                     "parses"))
            continue
        for code, reason in entries:
            sup[(site.block_idx, site.op_idx, code)] = reason
    return sup, malformed


def run_checks(program: Program,
               only: Optional[Iterable[str]] = None,
               collect_suppressed: Optional[list] = None,
               collect_timings: Optional[Dict[str, float]] = None
               ) -> List[Diagnostic]:
    """Run every registered checker (or the `only` subset of codes)
    over `program`; returns diagnostics sorted error-first, stable
    within severity. Diagnostics anchored at an op carrying a matching
    ``_pta_suppress`` attr are dropped from the return value and — when
    `collect_suppressed` is a list — appended to it as
    (diagnostic, reason) pairs so callers (CLI --json, the CI
    baseline) can count and surface them. `collect_timings`
    accumulates per-checker wall seconds (code -> s) across calls —
    the CLI's --json surfaces the totals so a slow checker is
    attributable instead of a mystery in the gate's wall-time pin."""
    import time as _time

    codes = set(only) if only is not None else None
    out: List[Diagnostic] = []
    for checker in registered_checkers():
        if codes is not None and checker.code not in codes:
            continue
        t0 = _time.perf_counter() if collect_timings is not None \
            else 0.0
        out.extend(checker.fn(program))
        if collect_timings is not None:
            collect_timings[checker.code] = collect_timings.get(
                checker.code, 0.0) + (_time.perf_counter() - t0)
    sup, malformed = _collect_suppressions(program)
    if malformed and (codes is None or "PTA199" in codes):
        out.extend(malformed)
    if sup:
        kept = []
        for d in out:
            reason = None
            if d.op_idx is not None:
                reason = sup.get((d.block_idx, d.op_idx, d.code))
            if reason is None:
                kept.append(d)
            elif collect_suppressed is not None:
                collect_suppressed.append((d, reason))
        out = kept
    rank = {ERROR: 0, WARNING: 1, INFO: 2}
    out.sort(key=lambda d: (rank.get(d.severity, 3), d.code,
                            d.block_idx, d.op_idx or 0))
    return out


def format_diagnostics(diags: Iterable[Diagnostic]) -> str:
    return "\n".join(d.format() for d in diags)


# ---------------------------------------------------------------------------
# Dataflow checks: PTA001 uninitialized read, PTA002 multi-writer,
# PTA003 dead op, PTA004 go-capture hazards.
# ---------------------------------------------------------------------------
def _seed_names(blk: Block, container: Optional[Operator]) -> set:
    """Names defined before any op of `blk` runs: persistables (from
    the scope after the startup program), declared data vars (feeds),
    and — for sub-blocks — the containing op's declared environment
    (control-flow kernels build a FRESH env; see block_entry_names)."""
    seeded = set()
    b: Optional[Block] = blk
    while b is not None:
        for v in b.vars.values():
            if v.persistable or v.is_data:
                seeded.add(v.name)
        b = b.parent_block
    if container is not None:
        seeded |= block_entry_names(container)
    return seeded


@register_checker("PTA001", "uninitialized-read")
def check_uninitialized_reads(program: Program):
    """A var read before any write that is neither persistable (scope
    state), a declared data var (feed), nor part of a sub-block's
    declared environment. At run time this is the Executor's
    'used before initialization' error or a trace-time KeyError —
    warning severity because an undeclared name CAN still be fed."""
    for blk, container in iter_blocks(program):
        seeded = _seed_names(blk, container)
        written = set()
        for i, op in enumerate(blk.ops):
            if op.type in _PLUMBING:
                continue
            for n in op.input_arg_names:
                if n == EMPTY_VAR or n in written or n in seeded:
                    continue
                site = OpSite(blk.idx, i, op, container)
                yield _diag_at(
                    "PTA001", WARNING, site,
                    f"var {n!r} is read before any write in the block "
                    f"and is neither persistable nor a declared data "
                    f"var", var=n,
                    hint="feed it, declare it with layers.data(...), "
                         "or produce it before this op")
                seeded.add(n)  # one diagnostic per name per block
            written.update(op.output_arg_names)


@register_checker("PTA002", "multi-writer")
def check_multi_writers(program: Program):
    """A non-persistable var written by more than one op in a block.
    Legal (last-writer-wins under the trace), but it makes the value
    observed by threads (go), recompute clones, and human readers
    order-dependent — the exact ambiguity _launch_go_ops refuses at
    run time. Info severity; the go-specific EP is PTA004."""
    for blk, container in iter_blocks(program):
        df = analyze_block(blk)
        for name, idxs in df.multi_writers().items():
            var = blk._find_var_recursive(name)
            if var is not None and var.persistable:
                continue  # in-place state updates are the normal idiom
            op = blk.ops[idxs[1]]
            site = OpSite(blk.idx, idxs[1], op, container)
            yield _diag_at(
                "PTA002", INFO, site,
                f"var {name!r} has {len(idxs)} writers in this block "
                f"(ops {idxs})", var=name,
                hint="rename intermediate results or route the value "
                     "through a persistable var if threads/clones "
                     "must observe a specific write")


@register_checker("PTA003", "dead-op")
def check_dead_ops(program: Program):
    """An op none of whose outputs is ever read anywhere in the
    program, written to a persistable (scope state), or side-effecting.
    XLA dead-codes it, but it still costs build/trace time and usually
    marks builder bugs. Info severity: fetch targets are unknown
    statically, so the last producer of a to-be-fetched var looks
    dead here."""
    read_anywhere = set()
    for blk, _ in iter_blocks(program):
        for op in blk.ops:
            read_anywhere.update(op.input_arg_names)
            for v in op.attrs.values():
                if isinstance(v, (list, tuple)) and v and all(
                        isinstance(x, str) for x in v):
                    read_anywhere.update(v)
    for site in iter_ops(program):
        op = site.op
        if op.type in _PLUMBING or not op.output_arg_names:
            continue
        if is_registered(op.type) and get_op_info(op.type).host_effect:
            continue
        if any(isinstance(v, Block) for v in op.attrs.values()):
            continue
        live = False
        for n in op.output_arg_names:
            var = site.op.block._find_var_recursive(n) \
                if site.op.block is not None else None
            if n in read_anywhere or (var is not None and
                                      var.persistable):
                live = True
                break
        if not live:
            yield _diag_at(
                "PTA003", INFO, site,
                f"no output of this op ({op.output_arg_names}) is read "
                f"anywhere, persistable, or side-effecting",
                hint="drop the op, or fetch/persist its result")


@register_checker("PTA004", "go-capture-hazard")
def check_go_captures(program: Program):
    """Static form of the _launch_go_ops run-time refusals: a `go` op
    capture that is (a) first written AFTER the go op, (b) written by
    multiple ops before it (ambiguous recompute), or (c) produced by a
    host-effecting op (recomputing doubles its side effects). All
    three raise at run time today — this surfaces them at build."""
    for blk, container in iter_blocks(program):
        df = analyze_block(blk)
        for go_idx, op in enumerate(blk.ops):
            if op.type != "go":
                continue
            site = OpSite(blk.idx, go_idx, op, container)
            for n in op.inputs.get("X", []):
                var = blk._find_var_recursive(n)
                if var is not None and (var.persistable or var.is_data):
                    continue
                writes = df.writers.get(n, [])
                before = [i for i in writes if i < go_idx]
                if not before:
                    if writes:
                        yield _diag_at(
                            "PTA004", ERROR, site,
                            f"go captures {n!r}, first written by op "
                            f"{writes[0]} AFTER the go op — the "
                            f"reference's eager executor would not "
                            f"observe it at the go point", var=n)
                    else:
                        yield _diag_at(
                            "PTA004", ERROR, site,
                            f"go captures {n!r} which is neither fed, "
                            f"persistable, nor produced by the block",
                            var=n)
                    continue
                if len(before) > 1:
                    yield _diag_at(
                        "PTA004", ERROR, site,
                        f"go captures {n!r} which has multiple writers "
                        f"before the go op (ops {before}); recomputing "
                        f"it in the go thread is ambiguous", var=n,
                        hint="route the value through a persistable "
                             "var")
                    continue
                producer = blk.ops[before[0]]
                if is_registered(producer.type) and \
                        get_op_info(producer.type).host_effect:
                    yield _diag_at(
                        "PTA004", ERROR, site,
                        f"go captures {n!r} produced by host-effecting "
                        f"op {producer.type!r}; recomputing it in the "
                        f"go thread would double its side effects",
                        var=n,
                        hint="route the value through a persistable "
                             "var")


# ---------------------------------------------------------------------------
# PTA010/PTA011: collectives inside divergent control flow.
# ---------------------------------------------------------------------------
def _is_collective(op: Operator) -> bool:
    if op.type in DIST_OP_TYPES:
        return True
    # an explicit shard_map axis on any op (sync_batch_norm and
    # friends) makes its kernel emit lax.psum over that axis
    return bool(op.attrs.get("axis_name"))


def _walk_block_ops(blk: Block, seen=None):
    """All ops in blk and (recursively) its sub-block attrs."""
    if seen is None:
        seen = set()
    for i, op in enumerate(blk.ops):
        yield i, op
        for _, sub in iter_sub_blocks(op):
            if id(sub) in seen:
                continue
            seen.add(id(sub))
            yield from _walk_block_ops(sub, seen)


def _prover_coverage(program: Program):
    """Op ids the PTA130 prover covers (every site it walked under a
    traced guard), or None when the prover is unavailable for this
    program (fixpoint failed to converge / raised) — the legacy
    PTA010/011 pattern matchers only emit at sites the prover does
    NOT cover, so each incident surfaces exactly once, with the
    proof-carrying diagnostic when one exists (the twin-diagnostic
    dedupe; the gate test pins the superset relation)."""
    from . import absint

    try:
        facts = absint.analyze(program)
    except Exception:
        return None
    if not facts.converged:
        return None
    return {id(site.op) for site, _g in facts.guarded_sites()}


@register_checker("PTA010", "collective-in-divergent-branch")
def check_collective_in_branch(program: Program):
    """NO collective may live inside divergent control flow: devices
    (or processes, for the io_callback pserver ops) that take
    different branches disagree on whether — or in which order — the
    collective executes, and the program deadlocks. This is the r5
    shard_map + lax.cond trap (CLAUDE.md) as a build-time error; the
    reference had no equivalent because its executor ran branches on
    the host.

    Sites the absint prover covers are left to PTA130, which carries
    the same ERROR stance plus the divergence proof — this pattern
    matcher is the FALLBACK for programs the fixpoint engine cannot
    analyze, so the two never double-report one incident."""
    covered = _prover_coverage(program)
    for blk, container in iter_blocks(program):
        for i, op in enumerate(blk.ops):
            if op.type not in DIVERGENT_CONTAINERS:
                continue
            for attr_name, sub in iter_sub_blocks(op):
                for j, inner in _walk_block_ops(sub):
                    if _is_collective(inner):
                        if covered is not None and \
                                id(inner) in covered:
                            continue  # PTA130 proves this site
                        site = OpSite(blk.idx, i, op, container)
                        yield _diag_at(
                            "PTA010", ERROR, site,
                            f"collective op {inner.type!r} (sub-block "
                            f"{attr_name} op {j}) lives inside "
                            f"divergent control flow ({op.type}); "
                            f"participants taking different paths "
                            f"will deadlock",
                            var=(inner.output_arg_names or [None])[0],
                            hint="hoist the collective out of the "
                                 "branch and mask its input instead "
                                 "(psum of a zeroed contribution is "
                                 "the identity)")


@register_checker("PTA011", "scope-collective-in-branch")
def check_scope_collective_in_branch(program: Program):
    """Ops that lower to shard_map collectives only when a parallel
    scope (context/expert parallel) is active, found inside divergent
    control flow. Warning: single-device lowering is fine, but the
    same program under scope_context_parallel/expert_parallel plants
    a collective in the branch — the r6 generalized GSPMD trap.
    Like PTA010, sites the absint prover covers are left to PTA130
    (which also upgrades them to ERROR under a proven-divergent
    guard); this matcher is the non-convergence fallback."""
    covered = _prover_coverage(program)
    for blk, container in iter_blocks(program):
        for i, op in enumerate(blk.ops):
            if op.type not in DIVERGENT_CONTAINERS:
                continue
            found: Dict[str, int] = {}
            for attr_name, sub in iter_sub_blocks(op):
                for _, inner in _walk_block_ops(sub):
                    if inner.type in SCOPE_COLLECTIVE_OP_TYPES:
                        if covered is not None and \
                                id(inner) in covered:
                            continue
                        found[inner.type] = found.get(inner.type, 0) + 1
            for inner_type, count in sorted(found.items()):
                site = OpSite(blk.idx, i, op, container)
                yield _diag_at(
                    "PTA011", WARNING, site,
                    f"{count} {inner_type!r} op(s) inside this "
                    f"{op.type}'s sub-blocks lower to shard_map "
                    f"collectives under context/expert-parallel "
                    f"scopes; there they become branch-internal "
                    f"collectives and deadlock",
                    hint=f"keep parallel-scope models' {inner_type} "
                         "ops out of divergent branches, or run this "
                         "program only outside those scopes")


# ---------------------------------------------------------------------------
# PTA020: while-carry dtype stability.
# ---------------------------------------------------------------------------
def _is_int_dtype_str(s: Optional[str]) -> bool:
    return bool(s) and s.startswith(("int", "uint", "bool"))


def _writer_dtype_map(program: Program) -> Dict[str, str]:
    """name -> dtype attr of its FIRST writer op carrying an explicit
    dtype (fill_constant / cast / ...), in program walk order. One
    pass, shared by every increment check in the run — writer attrs
    beat the Variable's dtype field, because build-time shape
    inference OVERWRITES an in-place op's shared var dtype with the
    (possibly already promoted) inferred result."""
    out: Dict[str, str] = {}
    for site in iter_ops(program):
        dt = site.op.attrs.get("dtype") or \
            site.op.attrs.get("out_dtype")
        if not isinstance(dt, str):
            continue
        for n in site.op.output_arg_names:
            out.setdefault(n, dt)
    return out


@register_checker("PTA020", "while-carry-dtype")
def check_while_carry_dtypes(program: Program):
    """`increment(x, 1.0)` on an integer var promotes the value to
    float under JAX weak typing; if the var is a lax.while_loop carry
    the loop raises a carry-structure TypeError deep inside the trace
    (CLAUDE.md: 'pass int steps'). Error inside while bodies, warning
    elsewhere (the counter silently changes dtype)."""
    in_while = set()
    for blk, _ in iter_blocks(program):
        for op in blk.ops:
            if op.type == "while":
                for _, sub in iter_sub_blocks(op):
                    for _, inner in _walk_block_ops(sub):
                        in_while.add(id(inner))
    writer_dtypes = None  # built lazily: most programs have 0 hits
    for site in iter_ops(program):
        op = site.op
        if op.type != "increment":
            continue
        step = op.attrs.get("step", 1.0)
        if not isinstance(step, float):
            continue
        names = op.inputs.get("X", [])
        if not names:
            continue
        if writer_dtypes is None:
            writer_dtypes = _writer_dtype_map(program)
        var = site.op.block._find_var_recursive(names[0])
        dtype = writer_dtypes.get(names[0]) or (
            var.dtype.value if var is not None and var.dtype is not None
            else None)
        if not _is_int_dtype_str(dtype):
            continue
        severity = ERROR if id(op) in in_while else WARNING
        yield _diag_at(
            "PTA020", severity, site,
            f"increment of integer var {names[0]!r} "
            f"(dtype {dtype}) with float step {step!r} "
            f"promotes the value to float"
            + (" and breaks the lax.while_loop carry dtype"
               if severity == ERROR else ""),
            var=names[0],
            hint="pass an int step: layers.increment(x, 1)")


# ---------------------------------------------------------------------------
# PTA030/PTA031: structural uid preservation for sampling ops.
# ---------------------------------------------------------------------------
def _needs_rng(op_type: str) -> bool:
    return is_registered(op_type) and get_op_info(op_type).needs_rng


def _is_recompute_clone(op: Operator) -> bool:
    return any(RECOMP_MARK in n for n in op.output_arg_names) or (
        op.attrs.get("op_role") == "backward")


@register_checker("PTA030", "sampling-uid-collision")
def check_sampling_uids(program: Program):
    """Sampling ops derive their PRNG salt from `op._uid`
    (fold_in(step_key, uid), core/registry.py OpContext.rng). Two
    DIFFERENT sampling ops sharing a uid draw byte-identical noise —
    silently correlated dropout masks. The one legal duplicate is a
    recompute clone (backward.py _emit_recompute), which shares its
    forward op's uid ON PURPOSE so the re-tossed noise matches."""
    groups: Dict[int, List[OpSite]] = {}
    for site in iter_ops(program):
        if _needs_rng(site.op.type):
            groups.setdefault(site.op._uid, []).append(site)
    for uid, sites in groups.items():
        if len(sites) < 2:
            continue
        types = {s.op.type for s in sites}
        originals = [s for s in sites
                     if not _is_recompute_clone(s.op)]
        if len(types) == 1 and len(originals) <= 1:
            continue  # forward op + its recompute clones: intended
        site = sites[1]
        yield _diag_at(
            "PTA030", ERROR, site,
            f"{len(sites)} sampling ops share uid {uid} "
            f"(types {sorted(types)}, anchors "
            f"{[s.anchor() for s in sites]}); their PRNG salts "
            f"collide and they draw identical noise",
            hint="ops cloned outside Program.clone/recompute must "
                 "re-derive or preserve _uid correctly (see "
                 "Operator.__init__)")


def check_clone_uids(src: Program, cloned: Program) -> List[Diagnostic]:
    """PTA031: verify a Program.clone (or any structural copy)
    preserved `_uid` on sampling ops — a clone that re-derives uids
    breaks fwd/bwd noise parity for programs sharing a scope with the
    source (CLAUDE.md architecture invariant). Ops are matched by
    (type, output names) signature since for_test clones prune ops."""
    out: List[Diagnostic] = []
    src_uids: Dict[tuple, int] = {}
    for site in iter_ops(src):
        if _needs_rng(site.op.type):
            sig = (site.op.type, tuple(site.op.output_arg_names))
            src_uids.setdefault(sig, site.op._uid)
    for site in iter_ops(cloned):
        if not _needs_rng(site.op.type):
            continue
        sig = (site.op.type, tuple(site.op.output_arg_names))
        want = src_uids.get(sig)
        if want is not None and site.op._uid != want:
            out.append(_diag_at(
                "PTA031", ERROR, site,
                f"cloned sampling op {site.op.type!r} has uid "
                f"{site.op._uid} but the source op (same outputs "
                f"{list(site.op.output_arg_names)}) has uid {want}: "
                f"the clone draws DIFFERENT noise",
                hint="clones must copy op._uid (Program.clone does; "
                     "custom passes must too)"))
    return out


# ---------------------------------------------------------------------------
# PTA040: recompute clones rooted in optimization_barrier.
# ---------------------------------------------------------------------------
@register_checker("PTA040", "recompute-barrier-rooting")
def check_recompute_barriers(program: Program):
    """Recompute clones (@RECOMP outputs) must read ONLY barriered
    (@BAR) or recomputed (@RECOMP) inputs: a clone reading the
    original forward activation is byte-identical HLO and XLA CSE
    merges it back, silently undoing the memory saving (backward.py
    _emit_recompute). Also verifies every @BAR name is actually
    produced by an optimization_barrier op."""
    barrier_outs = set()
    for site in iter_ops(program):
        if site.op.type == "optimization_barrier":
            barrier_outs.update(site.op.output_arg_names)
    for site in iter_ops(program):
        op = site.op
        if not any(RECOMP_MARK in n for n in op.output_arg_names):
            continue
        for n in op.input_arg_names:
            if n == EMPTY_VAR or RECOMP_MARK in n:
                continue
            if BARRIER_MARK in n:
                if n not in barrier_outs:
                    yield _diag_at(
                        "PTA040", ERROR, site,
                        f"recompute clone reads {n!r} which no "
                        f"optimization_barrier op produces", var=n)
                continue
            yield _diag_at(
                "PTA040", ERROR, site,
                f"recompute clone reads forward var {n!r} directly; "
                f"without an optimization_barrier root XLA CSE merges "
                f"the clone back into the forward op and the "
                f"rematerialization silently vanishes", var=n,
                hint="route out-of-region reads through "
                     "optimization_barrier (backward.py _emit_"
                     "recompute._bar)")


# ---------------------------------------------------------------------------
# PTA050/PTA051: parameter naming across builds.
# ---------------------------------------------------------------------------
@register_checker("PTA050", "auto-param-names")
def check_auto_param_names(program: Program):
    """Auto-generated parameter names (fc_N.w_M ...) come from ONE
    global helper counter: two programs built in different op orders
    assign the SAME name to DIFFERENT parameters, so sharing weights
    by name across separate train/decode builds breaks (CLAUDE.md
    late-r2 learning). Info severity per program — it only bites when
    a second build shares the scope; PTA051 (check_shared_params)
    upgrades it when two programs are actually paired."""
    auto = sorted(n for n in program._parameters
                  if _AUTO_PARAM_RE.search(n))
    if auto:
        sample = ", ".join(auto[:4]) + ("..." if len(auto) > 4 else "")
        yield Diagnostic(
            "PTA050", INFO,
            f"{len(auto)} parameter(s) carry auto-generated names "
            f"({sample}); cross-program weight sharing by these names "
            f"depends on identical build order",
            hint="name parameters explicitly (ParamAttr(name=...)) "
                 "for any model with a separate decode/inference "
                 "build — see models/transformer.py enc{i}_*/dec{i}_*")


def check_cross_model_collision(a: Program,
                                b: Program) -> List[Diagnostic]:
    """PTA100: lint two UNRELATED programs that will be co-resident
    in one process/scope (the multi-tenant serving runtime's model
    zoo, inference/runtime). Unlike PTA051 — where sharing is the
    INTENT and only broken sharing is flagged — here ANY persistable
    name overlap is an ERROR: same name + different shape means one
    model's init/swap clobbers the other (a shape error at best),
    same name + same shape means silent weight aliasing — model B
    quietly serves model A's parameters and every answer is wrong
    with no error anywhere. The aliasing case is the WORSE defect
    (no error ever surfaces), so it must not rank below the loud
    one: both are errors and both fail the --strict gate. Diagnosed
    from the runtime scheduling work (ModelRegistry.load refuses
    colliding co-loads with this check); the fix is per-model name
    prefixes (the runtime zoo's ``{prefix}_fc1.w`` scheme) or
    per-model Scopes.

    Covers ALL persistable vars, not just parameters: batch_norm's
    moving mean/variance are persistables created via
    create_global_variable (never registered in ``_parameters``), and
    two models saved from fresh processes both carry e.g.
    ``batch_norm_0...`` names — a parameters-only intersection stays
    silent on exactly the running-statistics aliasing this check
    exists to catch."""
    out: List[Diagnostic] = []

    def persistables(p: Program):
        vars_by_name = {}
        for v in p.list_vars():
            if getattr(v, "persistable", False):
                vars_by_name.setdefault(v.name, v)
        return vars_by_name

    pa, pb = persistables(a), persistables(b)
    for name in sorted(set(pa) & set(pb)):
        sa = pa[name].shape
        sb = pb[name].shape
        if sa is not None and sb is not None \
                and tuple(sa) != tuple(sb):
            out.append(Diagnostic(
                "PTA100", ERROR,
                f"co-resident models both declare persistable {name!r} "
                f"with DIFFERENT shapes {tuple(sa)} vs {tuple(sb)}: "
                f"loading both into one scope clobbers one of them",
                var=name,
                hint="give each model its own Scope, or prefix its "
                     "parameter names (ParamAttr(name='<model>_...'))"))
        elif sa is None or sb is None:
            out.append(Diagnostic(
                "PTA100", ERROR,
                f"co-resident models both declare persistable {name!r} "
                f"(shape unknown on at least one side): one scope "
                f"would alias or clobber their weights", var=name,
                hint="give each model its own Scope, or prefix its "
                     "parameter names (ParamAttr(name='<model>_...'))"))
        else:
            out.append(Diagnostic(
                "PTA100", ERROR,
                f"co-resident models both declare persistable {name!r} "
                f"at the same shape: one scope would silently ALIAS "
                f"their weights (model B serves model A's "
                f"parameters, no error anywhere)", var=name,
                hint="give each model its own Scope, or prefix its "
                     "parameter names (ParamAttr(name='<model>_...'))"))
    return out


def check_shared_params(a: Program, b: Program) -> List[Diagnostic]:
    """PTA051: lint a (train, inference) program pair that shares
    weights by name through one scope. Shared names with DIFFERENT
    shapes are errors (the share is already broken); shared
    auto-generated names are warnings (one added layer reorders the
    global counter and silently shuffles every weight)."""
    out: List[Diagnostic] = []
    shared = sorted(set(a._parameters) & set(b._parameters))
    for name in shared:
        sa = a._parameters[name].shape
        sb = b._parameters[name].shape
        if sa is not None and sb is not None and tuple(sa) != tuple(sb):
            out.append(Diagnostic(
                "PTA051", ERROR,
                f"programs share parameter {name!r} with mismatched "
                f"shapes {tuple(sa)} vs {tuple(sb)}: scope sharing by "
                f"this name is broken", var=name))
        elif _AUTO_PARAM_RE.search(name):
            out.append(Diagnostic(
                "PTA051", WARNING,
                f"programs share AUTO-generated parameter name "
                f"{name!r}; any build-order divergence re-assigns it "
                f"to a different weight", var=name,
                hint="use explicit ParamAttr names in both builds"))
    return out


# ---------------------------------------------------------------------------
# PTA060: @SEQ_LEN companion declaration/batch consistency.
# ---------------------------------------------------------------------------
SEQ_LEN_SUFFIX = "@SEQ_LEN"


@register_checker("PTA060", "seq-len-companion")
def check_seq_len_companions(program: Program):
    """Padded sequences ride with an int32 [batch] `name@SEQ_LEN`
    companion (layers/sequence.py). Build-time shape probes replace -1
    dims with a probe value, so a program whose data var has a
    CONCRETE batch must declare the companion at the SAME concrete
    batch — a (-1,) companion probes at a different batch and the
    kernel trace fails with an opaque broadcast error (CLAUDE.md
    late-r2 learning). Companions read by ops but declared nowhere
    get a warning (the feed path would KeyError)."""
    written = set()
    declared = set()
    for blk, _ in iter_blocks(program):
        declared.update(blk.vars)
        for op in blk.ops:
            written.update(op.output_arg_names)
    # companions READ by some op but declared in no block: the program
    # expects a feed it never announces (DataFeeder/_check_feed_shape
    # cannot validate it; the trace KeyErrors)
    flagged = set()
    for site in iter_ops(program):
        for n in site.op.input_arg_names:
            if not n.endswith(SEQ_LEN_SUFFIX) or n in declared \
                    or n in written or n in flagged:
                continue
            flagged.add(n)
            yield _diag_at(
                "PTA060", WARNING, site,
                f"op reads sequence-length companion {n!r} which no "
                f"block declares; the feed path cannot validate it "
                f"and the trace will KeyError", var=n,
                hint="declare it (layers.sequence.seq_len_of / "
                     "bind_seq_len) or create the data var explicitly")
    for blk, container in iter_blocks(program):
        for name, var in blk.vars.items():
            if not name.endswith(SEQ_LEN_SUFFIX):
                continue
            if name in written and not var.is_data:
                # produced in-graph (bind_seq_len assign): shape
                # inference rewrites its shape from the producer, so
                # the declared placeholder shape is not a feed contract
                continue
            base = blk._find_var_recursive(name[:-len(SEQ_LEN_SUFFIX)])
            if base is None or base.shape is None:
                continue
            batch = base.shape[0] if len(base.shape) else None
            if batch is None or batch == -1:
                continue
            cshape = var.shape
            if cshape is None or tuple(cshape) != (batch,):
                yield Diagnostic(
                    "PTA060", ERROR,
                    f"companion {name!r} is declared with shape "
                    f"{tuple(cshape) if cshape else None} but its base "
                    f"var has CONCRETE batch {batch}; build-time shape "
                    f"probes will disagree", block_idx=blk.idx,
                    var=name,
                    hint=f"declare the companion at shape ({batch},) "
                         f"(models/machine_translation.py "
                         f"build_decode_program does)")


# ---------------------------------------------------------------------------
# PTA070: host_effect flag completeness (registry-level).
# ---------------------------------------------------------------------------
def check_registry(op_types: Optional[Iterable[str]] = None
                   ) -> List[Diagnostic]:
    """PTA070: every registered kernel whose code references
    io_callback/pure_callback must be flagged host_effect=True —
    otherwise Executor.run_steps lowers it into a device-resident
    lax.scan and its once-per-step host semantics silently break
    (CLAUDE.md r6 'REMEMBER the flag', mechanized). register_op now
    asserts this at registration; this sweep is the belt-and-braces
    for kernels registered before the assert or monkeypatched in."""
    from ..core.registry import registered_ops

    out: List[Diagnostic] = []
    types = list(op_types) if op_types is not None else registered_ops()
    for t in types:
        if not is_registered(t):
            continue
        info = get_op_info(t)
        if info.host_effect:
            continue
        if kernel_bridges_host(info.kernel):
            out.append(Diagnostic(
                "PTA070", ERROR,
                f"op {t!r} kernel references io_callback/pure_callback "
                f"but is registered with host_effect=False; "
                f"Executor.run_steps would scan it on device and break "
                f"its per-step host semantics", op_type=t,
                hint="register with host_effect=True"))
    return out


@register_checker("PTA070", "host-effect-flag")
def check_program_host_effects(program: Program):
    """Registry sweep restricted to the op types this program uses."""
    used = {site.op.type for site in iter_ops(program)}
    for d in check_registry(sorted(used)):
        yield d


# ---------------------------------------------------------------------------
# PTA090: write-only persistables must be carry-declarable.
# ---------------------------------------------------------------------------
@register_checker("PTA090", "write-only-carry")
def check_write_only_carry(program: Program):
    """A persistable var a step program WRITES but never READS (KV
    slots / counters / stats written for the next consumer) does not
    flow through the executor's state-in path: Executor.run_steps and
    PreparedProgram(steps=K) must seed it into the lax.scan carry with
    zeros or the carry structure changes between iterations — the r6
    write-only-carry trap. That zeros slot is declared from the var's
    metadata, so the var must be CARRY-DECLARABLE: a known dtype and a
    concrete shape (no -1 / missing dims). A write-only persistable
    that is not breaks the K-step scan (and its disk-cached
    rehydration) with an opaque tree-structure error deep in jax;
    error severity because the program is one run_steps call away
    from it.

    Reads anywhere count — including inside While/cond sub-blocks,
    whose parent-visible reads surface as the container op's input
    slots — so ordinary read-modify-write state (params, optimizer
    moments, counters) never trips this."""
    read = set()
    for site in iter_ops(program):
        read.update(site.op.input_arg_names)
    blk = program.global_block
    df = analyze_block(blk)
    flagged = set()
    for name in df.writers:
        if name in read or name in flagged or name == EMPTY_VAR:
            continue
        var = blk._find_var_recursive(name)
        if var is None or not var.persistable:
            continue
        problems = []
        if var.dtype is None:
            problems.append("no dtype")
        if var.shape is None:
            problems.append("no declared shape")
        elif any(d is None or d < 0 for d in var.shape):
            problems.append(f"non-concrete shape {tuple(var.shape)}")
        if not problems:
            continue
        flagged.add(name)
        first = df.first_write[name]
        op = blk.ops[first]
        yield Diagnostic(
            "PTA090", ERROR,
            f"persistable {name!r} is write-only in this program but "
            f"not carry-declarable ({'; '.join(problems)}): "
            f"Executor.run_steps / prepare(steps=K) must seed its "
            f"scan-carry slot with zeros of the declared shape/dtype",
            block_idx=blk.idx, op_idx=first, op_type=op.type, var=name,
            hint="declare it with a concrete shape and dtype "
                 "(models/decode_engine._declare_slot_state does), or "
                 "read-modify-write it so it rides state_in")


# ---------------------------------------------------------------------------
# PTA110: shared-pool writes must be provably lane-exclusive.
# ---------------------------------------------------------------------------
# the pool name mark is OWNED by the ownership domain (absint) —
# importing it keeps this sweep and the prover matching the same
# vars (the PTA180/TEL_MARK drifted-literal lesson);
# models/decode_engine.py re-declares the literal only because
# analysis never imports models
from .absint import POOL_MARK  # noqa: E402

# the builder-declared reasons row indices of a shared-pool write
# cannot alias (layers/extras.py masked_pool_write documents all
# three; "cow_dst" is the COW copy's fresh-exclusive destination
# window — the radix/beam branching path)
_POOL_EXCLUSIVE_VIA = ("block_table", "host_indices", "cow_dst")


def _ownership_coverage(program: Program):
    """Op ids of the @POOL write sites the ownership prover covers
    (every pool access absint's converged fixpoint recorded), or None
    when the prover is unavailable for this program — the PTA110
    declaration checker only emits at sites the prover does NOT
    cover, so each incident surfaces exactly once, with the
    proof-carrying PTA191/190/192 diagnostic when one exists (the
    PTA010/PTA130 twin-dedupe pattern applied to ownership)."""
    from . import absint

    try:
        facts = absint.analyze(program)
    except Exception:
        return None
    if not facts.converged:
        return None
    return {id(acc.site.op) for acc in facts.pool_accesses
            if acc.kind == "write"}


@register_checker("PTA110", "shared-pool-write-exclusive")
def check_shared_pool_writes(program: Program):
    """Writes into a SHARED decode KV block pool (persistable vars
    carrying the @POOL name mark — models/decode_engine.py paged
    layout) must be provably lane-exclusive: unlike the per-lane
    dense buffers, a pool cell is not owned by a row index, so an
    aliased or unmasked scatter silently corrupts ANOTHER request's
    KV — generations stay plausible and no error ever surfaces,
    which makes this the nastiest paged-serving failure class.

    Provably exclusive means: the ONE blessed writer op
    (``masked_pool_write``: disjoint one-hot masks, clamped keep
    mask), reading the pool it writes (read-modify-write, so the
    pool rides the executor's state_in path instead of tripping the
    PTA090 write-only-carry trap), carrying the builder's
    ``exclusive_via`` declaration ('block_table' = per-lane blocks
    from the host free-list, 'host_indices' = host-deduplicated
    admission targets), and — for block-table writes — an active-lane
    ``Gate`` so idle/dustbin/paused lanes write nothing.

    Sites the ownership prover covers are left to PTA190/191/192,
    which carry the same ERROR stance plus the provenance PROOF —
    this declaration checker is the fallback for programs the
    fixpoint engine cannot analyze, so the two never double-report
    one incident (the PTA010/PTA130 dedupe pattern)."""
    covered = _ownership_coverage(program)
    for site in iter_ops(program):
        op = site.op
        hit = [n for n in op.output_arg_names if POOL_MARK in n]
        if not hit:
            continue
        if any(isinstance(v, Block) for v in op.attrs.values()):
            # container ops (while/cond) surface their sub-blocks'
            # writes as their own output slots; the actual writer
            # inside the sub-block is what this sweep judges
            continue
        var = op.block._find_var_recursive(hit[0]) \
            if op.block is not None else None
        if var is not None and not var.persistable:
            continue
        if covered is not None and id(op) in covered:
            continue  # the ownership prover judges this site
        name = hit[0]
        if op.type != "masked_pool_write":
            yield _diag_at(
                "PTA110", ERROR, site,
                f"op {op.type!r} writes shared block pool {name!r} "
                f"directly; only masked_pool_write's disjoint one-hot "
                f"scatter is provably lane-exclusive — anything else "
                f"is the silent cross-request KV corruption class",
                var=name,
                hint="route the write through layers.masked_pool_"
                     "write(pool, new, index, gate, exclusive_via=...)")
            continue
        if name not in op.input_arg_names:
            yield _diag_at(
                "PTA110", ERROR, site,
                f"masked_pool_write writes {name!r} without reading "
                f"it: the keep-mask read-modify-write is what "
                f"preserves other lanes' cells (and keeps the pool "
                f"on the state_in path — see PTA090)", var=name)
            continue
        via = op.attrs.get("exclusive_via")
        if via not in _POOL_EXCLUSIVE_VIA:
            yield _diag_at(
                "PTA110", ERROR, site,
                f"masked_pool_write into {name!r} carries "
                f"exclusive_via={via!r}; the builder must declare why "
                f"row indices cannot alias "
                f"({'/'.join(_POOL_EXCLUSIVE_VIA)})", var=name)
            continue
        if via == "block_table" and not op.inputs.get("Gate"):
            yield _diag_at(
                "PTA110", ERROR, site,
                f"block-table write into {name!r} has no Gate input: "
                f"idle/dustbin/paused lanes (active=0) would scatter "
                f"through stale table rows into blocks other lanes "
                f"own", var=name,
                hint="pass gate=cast(active, 'float32')")


# ---------------------------------------------------------------------------
# PTA120: speculative counter-advance bound.
# ---------------------------------------------------------------------------
@register_checker("PTA120", "spec-advance-bounded")
def check_spec_advance(program: Program):
    """The speculative decode step advances per-lane counters by
    ``spec_accept``'s Advance output, whose <= k+1 clamp (and the
    EOS/room clips) the kernel computes FROM the op's ``k`` and
    ``max_len`` attrs (ops/spec_ops.py). That bound is only provable
    when the attrs agree with the wired tensors: Proposals [R, k],
    DraftProbs [R, k, V], TargetProbs [R, k+1, V] — a builder that
    lies about k mis-slices the acceptance scan and the advance can
    exceed the verified positions. Likewise the accepted-prefix
    ``span_scatter`` consuming the Tokens output must write a
    [R, max_len] buffer, or the room clip bounds writes against the
    WRONG buffer width (per-lane counter corruption / out-of-buffer
    token writes — the silent class the accepted-prefix scatter can
    hide). Grown from the r14 draft-and-verify work."""
    # one walk up front: the Tokens-consumer sweep below would
    # otherwise re-walk the whole program per spec_accept site, and
    # the spec serve programs are the zoo's biggest builds
    spec_sites, scatter_sites = [], []
    for site in iter_ops(program):
        if site.op.type == "spec_accept":
            spec_sites.append(site)
        elif site.op.type == "span_scatter":
            scatter_sites.append(site)
    for site in spec_sites:
        op = site.op
        blk = op.block
        k = op.attrs.get("k")
        max_len = op.attrs.get("max_len")
        if not isinstance(k, int) or k < 0:
            yield _diag_at(
                "PTA120", ERROR, site,
                f"spec_accept carries k={k!r}; the advance bound "
                f"needs a static k >= 0")
            continue
        if not isinstance(max_len, int) or max_len < 1:
            yield _diag_at(
                "PTA120", ERROR, site,
                f"spec_accept carries max_len={max_len!r}; the room "
                f"clip needs the real decode-buffer width")
            continue

        def _shape(slot):
            names = op.inputs.get(slot) or []
            if not names or blk is None:
                return None
            v = blk._find_var_recursive(names[0])
            return tuple(v.shape) if v is not None and v.shape \
                else None

        for slot, axis, want in (("Proposals", 1, k),
                                 ("DraftProbs", 1, k),
                                 ("TargetProbs", 1, k + 1)):
            shape = _shape(slot)
            if shape is None or len(shape) <= axis:
                continue
            if shape[axis] != want:
                yield _diag_at(
                    "PTA120", ERROR, site,
                    f"spec_accept attr k={k} disagrees with its "
                    f"{slot} input (shape {shape}, axis {axis} "
                    f"expected {want}): the counter-advance <= k+1 "
                    f"bound is unprovable",
                    var=(op.inputs.get(slot) or [None])[0])
        # the accepted-prefix scatter: every span_scatter fed by this
        # op's Tokens must write a buffer of width max_len
        tok_names = set(op.outputs.get("Tokens") or [])
        if not tok_names:
            continue
        for other in scatter_sites:
            o = other.op
            if not tok_names & set(o.inputs.get("Vals") or []):
                continue
            buf_names = o.inputs.get("X") or []
            v = o.block._find_var_recursive(buf_names[0]) \
                if buf_names and o.block is not None else None
            shape = tuple(v.shape) if v is not None and v.shape \
                else None
            if shape is not None and len(shape) == 2 \
                    and shape[1] != max_len:
                yield _diag_at(
                    "PTA120", ERROR, other,
                    f"accepted-prefix span_scatter writes buffer "
                    f"{buf_names[0]!r} of width {shape[1]} but the "
                    f"producing spec_accept clips room against "
                    f"max_len={max_len}: the advance bound guards "
                    f"the wrong buffer", var=buf_names[0])


# ---------------------------------------------------------------------------
# PTA180: device-telemetry counter contract.
# ---------------------------------------------------------------------------
# the devtel registry owns the mark (single source of truth: a local
# copy drifting from the registry would make PTA180 silently match
# zero vars and unenforce the whole contract)
from ..observability.devtel import TEL_MARK  # noqa: E402


def _rmw_chain_reads(block, site_idx: int, name: str,
                     depth: int = 8) -> bool:
    """Does the value written to ``name`` at ``block.ops[site_idx]``
    derive from a read of ``name``? Direct read on the writing op
    counts (container ops carry the var through their inputs), else a
    bounded backward walk over same-block producers — the RMW idiom
    ``assign(elementwise_add(var, delta), output=var)`` reads the var
    one producer behind the write."""
    ops = block.ops
    op = ops[site_idx]
    if name in op.input_arg_names:
        return True
    producers = {}
    for i, o in enumerate(ops[:site_idx]):
        for out in o.output_arg_names:
            producers[out] = i   # last producer before the write wins
    frontier = [n for n in op.input_arg_names if n != name]
    seen = set(frontier)
    for _ in range(depth):
        nxt = []
        for n in frontier:
            pi = producers.get(n)
            if pi is None:
                continue
            po = ops[pi]
            if name in po.input_arg_names:
                return True
            for m in po.input_arg_names:
                if m not in seen:
                    seen.add(m)
                    nxt.append(m)
        if not nxt:
            return False
        frontier = nxt
    return False


@register_checker("PTA180", "telemetry-counter-contract")
def check_telemetry_counters(program: Program):
    """Device-telemetry counters (persistables carrying the ``@TEL``
    name mark — observability/devtel.py) are the flight recorder's
    only view into a fused admission+burst dispatch, and they ride
    the executor's state paths, so each one must honor the contract
    the measured traps behind PTA020 and PTA090 taught:

    * **declared int64** — an accidentally-float counter silently
      breaks the lax.while_loop / scan carry dtypes under JAX weak
      typing (the PTA020 `increment` promotion class, applied to the
      new subsystem);
    * **concrete declared shape + persistable** — the counter must be
      carry-declarable so `Executor.run_steps` / `prepare(steps=K)`
      can seed its scan slot (the PTA090 class);
    * **read-modify-write at EVERY writing site** — a write whose
      value does not derive from a read of the counter (checked per
      site via the producer chain, not a program-global read set: a
      legitimate RMW bump elsewhere must not whitewash a clobbering
      ``assign(fill_constant, output=var)``) overwrites the
      cumulative total, so the serving layer's per-dispatch deltas go
      negative and every window silently lies. Reads inside While
      bodies surface through the container op's carried inputs, so
      the serve programs' in-loop increments count.

    ERROR severity: a drifted counter poisons the telemetry surface
    with no error anywhere downstream — the defect class this whole
    checker family exists for."""
    written: Dict[str, OpSite] = {}
    clobbered: Dict[str, OpSite] = {}
    for blk, container in iter_blocks(program):
        for i, op in enumerate(blk.ops):
            for n in op.output_arg_names:
                if TEL_MARK not in n:
                    continue
                site = OpSite(blk.idx, i, op, container)
                written.setdefault(n, site)
                if n not in clobbered \
                        and not _rmw_chain_reads(blk, i, n):
                    clobbered[n] = site
    seen = set()
    for blk, _container in iter_blocks(program):
        for name, var in blk.vars.items():
            if TEL_MARK not in name or name in seen:
                continue
            seen.add(name)
            dtype = getattr(var, "_declared_dtype", None) or var.dtype
            dtype_name = np_dtype_name(dtype) if dtype is not None \
                else None
            shape = getattr(var, "_declared_shape", None)
            if shape is None:
                shape = tuple(var.shape) if var.shape is not None \
                    else None
            site = written.get(name)
            problems = []
            if not var.persistable:
                problems.append(
                    "not persistable (it would not ride "
                    "state_in/state_out across dispatches)")
            if dtype_name != "int64":
                problems.append(
                    f"declared dtype {dtype_name or 'unknown'} "
                    f"(must be int64: float counters break while/"
                    f"scan carry dtypes under weak typing)")
            if shape is None or any(d is None or d < 0
                                    for d in shape):
                problems.append(
                    f"non-concrete declared shape "
                    f"{tuple(shape) if shape else None} (must be "
                    f"carry-declarable for the K-step scan)")
            clobber = clobbered.get(name)
            if clobber is not None:
                site = clobber   # anchor the diagnostic at the bad
                #                  write, not just the first one
                problems.append(
                    "written without reading it (the update must be "
                    "read-modify-write — var = var + delta — at "
                    "every site, or per-dispatch deltas go negative)")
            if not problems:
                continue
            msg = (f"telemetry counter {name!r} violates the devtel "
                   f"contract: {'; '.join(problems)}")
            hint = ("declare it through observability/devtel."
                    "counter_specs ([1] int64 persistable) and "
                    "update it with layers.assign(elementwise_add("
                    "var, delta), output=var)")
            if site is not None:
                yield _diag_at("PTA180", ERROR, site, msg, var=name,
                               hint=hint)
            else:
                yield Diagnostic("PTA180", ERROR, msg,
                                 block_idx=blk.idx, var=name,
                                 hint=hint)


# ---------------------------------------------------------------------------
# PTA080: unregistered op types.
# ---------------------------------------------------------------------------
@register_checker("PTA080", "unregistered-op")
def check_registered(program: Program):
    """Every non-plumbing op must have a registered kernel, or the
    Executor raises at compile ('op has no registered kernel') —
    catch it before the jax trace starts."""
    for site in iter_ops(program):
        if site.op.type in _PLUMBING:
            continue
        if not is_registered(site.op.type):
            yield _diag_at(
                "PTA080", ERROR, site,
                f"op type {site.op.type!r} has no registered kernel "
                f"(core/registry.py)",
                hint="register the op or remove it from the program")


# ---------------------------------------------------------------------------
# PTA130/PTA131: the divergence & sharding prover (analysis/absint.py
# abstract interpretation — whole-program fixpoint over divergence
# contexts and the replication lattice).
# ---------------------------------------------------------------------------
def _guard_proof(facts, guards) -> str:
    lines = [g.describe() for g in guards]
    return "; ".join(lines)


@register_checker("PTA130", "divergence-proof-collective")
def check_collective_divergence_proof(program: Program):
    """The PROOF form of PTA010/011: for every collective site, the
    abstract interpreter computes the full guard context (every
    while/cond predicate the site executes under, transitively) and
    classifies each predicate on the replication lattice. A collective
    under ANY traced guard is an ERROR — same stance as PTA010, so
    PTA130's findings are a superset by construction — but the
    diagnostic now carries the proof: a guard PROVEN divergent names
    its divergence source and mint site (the r5 deadlock explained,
    not pattern-matched); an unprovable guard says what is missing;
    a value-uniform guard says which replication assumptions the
    safety would rest on. Scope-dependent collectives (attention/
    switch_moe under cp/ep scopes) mirror PTA011 at WARNING, upgraded
    to ERROR when a guard is proven divergent — under a per-lane/
    per-stage predicate the scoped lowering WILL deadlock."""
    from . import absint

    facts = absint.analyze(program)
    scope_hits: Dict[tuple, list] = {}
    for site, guards in facts.guarded_sites():
        op = site.op
        if _is_collective(op):
            proven = facts.divergent(guards)
            yield _diag_at(
                "PTA130", ERROR, site,
                f"collective op {op.type!r} executes under "
                f"{len(guards)} traced guard(s) "
                f"[{_guard_proof(facts, guards)}] — "
                + ("participants PROVABLY disagree on whether/in "
                   "which order it runs: deadlock" if proven else
                   "collective order under traced control flow "
                   "cannot be verified: hoist it"),
                var=(op.output_arg_names or [None])[0],
                hint="hoist the collective out of the branch and mask "
                     "its input instead (psum of a zeroed "
                     "contribution is the identity)")
        elif op.type in SCOPE_COLLECTIVE_OP_TYPES:
            key = (guards[-1].container_anchor, op.type)
            scope_hits.setdefault(key, []).append((site, guards))
    for (anchor, op_type), entries in sorted(scope_hits.items()):
        site, guards = entries[0]
        proven = facts.divergent(guards)
        sev = ERROR if proven else WARNING
        yield _diag_at(
            "PTA130", sev, site,
            f"{len(entries)} {op_type!r} op(s) under traced guard(s) "
            f"of {anchor} [{_guard_proof(facts, guards)}] lower to "
            f"shard_map collectives under context/expert-parallel "
            f"scopes"
            + (" — and the guard is PROVEN divergent, so the scoped "
               "lowering deadlocks" if proven else
               "; there they become branch-internal collectives "
               "and deadlock"),
            hint=f"keep parallel-scope models' {op_type} ops out of "
                 f"divergent branches, or run this program only "
                 f"outside those scopes")


@register_checker("PTA131", "replicated-in-divergent-context")
def check_replicated_in_divergent_context(program: Program):
    """The r5 trap family, proven from the replication lattice:

    (a) a grad op inside a divergent context producing a gradient for
        a REPLICATED forward input — the transpose of the implicit
        replicated->varying broadcast is a psum, and it lands INSIDE
        the branch: participants on other paths never post it, so the
        program deadlocks. The fix is the r5 `_vary` discipline: cast
        the input varying BEFORE the divergent region
        (absint.mark_divergence_source(v, "vary")) and mask-psum
        after.
    (b) a value carrying an auto-axis sharding annotation
        (absint.mark_sharded / a `sharding_axes` attr) consumed inside
        a divergent context — GSPMD is free to materialize the
        resharding collective at the consumption site, i.e. inside
        the branch (the r6 generalized trap: 1F1B x tp's
        vocab-sharded logits psum).

    ERROR when a guard is PROVEN divergent; WARNING when divergence is
    unprovable; silent when every guard is value-uniform (every mesh
    program instance takes the same path, so implied collectives
    match up — this is exactly what the uniformity proof buys)."""
    from . import absint

    facts = absint.analyze(program)
    for site, guards in facts.guarded_sites():
        if not facts.unproven(guards):
            continue  # all guards proven value-uniform
        sev = ERROR if facts.divergent(guards) else WARNING
        op = site.op
        is_grad = op.type.endswith("_grad") or \
            op.attrs.get("op_role") == "backward"
        if is_grad:
            flagged = set()
            for g in op.output_arg_names:
                if not g.endswith(GRAD_MARK) or g in flagged:
                    continue
                x = g[:-len(GRAD_MARK)]
                if facts.value(x).repl != "replicated":
                    continue  # varying input: the r5 fix was applied
                flagged.add(g)
                yield _diag_at(
                    "PTA131", sev, site,
                    f"grad op {op.type!r} differentiates "
                    f"REPLICATED input {x!r} inside divergent "
                    f"control flow [{_guard_proof(facts, guards)}]: "
                    f"the transpose of the implicit replicated->"
                    f"varying cast is a psum INSIDE the branch — "
                    f"participants on other paths never post it",
                    var=x,
                    hint="make the input varying BEFORE the branch "
                         "(absint.mark_divergence_source(v, 'vary')) "
                         "and mask-psum after — the r5 1F1B fix")
        for n in op.input_arg_names:
            if n == EMPTY_VAR:
                continue
            vf = facts.value(n)
            if vf.sharded is None:
                continue
            yield _diag_at(
                "PTA131", sev, site,
                f"op {op.type!r} consumes {n!r}, which carries the "
                f"auto-axis sharding annotation {vf.sharded} "
                f"(minted at {vf.minted_at}), inside divergent "
                f"control flow [{_guard_proof(facts, guards)}]: "
                f"GSPMD may materialize the resharding collective "
                f"at this site — inside the branch",
                var=n,
                hint="apply the sharding constraint OUTSIDE the "
                     "divergent region (CLAUDE.md r5: ONE "
                     "with_sharding_constraint on the pre-branch "
                     "value)")


GRAD_MARK = "@GRAD"


# ---------------------------------------------------------------------------
# PTA160/PTA161/PTA170: the sharding & resource provers (the sharding
# domain of analysis/absint.py — propagated ShardSpecs, implied
# collectives, and the static per-device memory planner).
# ---------------------------------------------------------------------------
_LOOP_CONTAINERS = ("while", "run_block_if")


def _in_loop(guards) -> bool:
    return any(g.container_type in _LOOP_CONTAINERS for g in guards)


def _event_where(es) -> str:
    out = f"{es.event.kind} over mesh axes {sorted(set(es.event.axes))}"
    if es.event.var:
        out += f" (var {es.event.var!r})"
    return out


@register_checker("PTA160", "sharding-contradiction")
def check_sharding_contradiction(program: Program):
    """Sharding-contradiction / implicit-reshard prover. Two failure
    classes, both read off the propagated spec facts:

    * **conflict** — consumers demand incompatible ShardSpecs for one
      value (an elementwise/concat joining a dim0-dp operand with a
      dim0-tp operand): GSPMD silently reshards one side. WARNING in
      straight-line code (a one-off reshard is a perf bug), ERROR
      under a serve-While / divergent guard (a reshard per tick, or a
      branch-internal collective — the deadlock class).
    * **reshard** — a single value whose layout GSPMD must change at
      this site (a reshape splitting a sharded dim off its major
      position, a producer disagreeing with a pinned annotation —
      the r5 'dp on the pre-reshape dim' trap). Silent in
      straight-line code (the facts record it; the planner prices
      it), ERROR inside a While body or divergent context.
    """
    from . import absint

    facts = absint.analyze(program)
    for es in facts.collective_events:
        if es.event.kind not in ("conflict", "reshard"):
            continue
        hot = _in_loop(es.guards) or facts.divergent(es.guards)
        if es.event.kind == "reshard" and not hot:
            continue  # a recorded fact, not a finding
        sev = ERROR if hot else WARNING
        where = ("inside a serve-While/divergent context "
                 f"[{_guard_proof(facts, es.guards)}]" if es.guards
                 else "in straight-line code")
        yield _diag_at(
            "PTA160", sev, es.site,
            f"sharding {es.event.kind}: {es.event.why} — {where}"
            + ("; GSPMD materializes the reshard collective INSIDE "
               "the loop/branch body, every iteration" if hot
               else ""),
            var=es.event.var,
            hint="apply ONE with_sharding_constraint on the value the "
                 "consumers actually share, OUTSIDE the divergent "
                 "region (CLAUDE.md r5: the post-reshape mb dim, not "
                 "the pre-reshape full-batch dim)")


@register_checker("PTA161", "collective-order-proof")
def check_collective_order(program: Program):
    """Collective-order agreement, proven symbolically: enumerate the
    sequence of collectives — literal collective ops AND the psum/
    allgather/reshard events the sharding domain proves the lowering
    implies — that each mesh coordinate observes, composing with the
    divergence lattice: a collective under a PROVEN-divergent guard
    is observed by the coordinates taking that path and NOT by the
    others, so the two coordinate classes disagree on the collective
    sequence and the program deadlocks (XLA collectives must be
    issued in identical order on every participant). ERROR with the
    divergence source named; WARNING when a guard's divergence is
    unprovable (order agreement cannot be verified).

    The 1F1B x tp rejection (pipeline_1f1b.py's named ValueError) is
    a COROLLARY here: a vocab/row-sharded matmul inside the per-stage
    F/B cond implies a psum over 'tp' under a 'pp_stage_id'-divergent
    guard — exactly the shape this prover rejects, for any future
    lowering, without naming schedules. Literal collective sites
    under guards are already PTA130 errors; this checker reports the
    sharding-IMPLIED events PTA130 cannot see, and carries the full
    observed-sequence enumeration in the diagnostic so the
    disagreement is readable, not asserted."""
    from . import absint

    facts = absint.analyze(program)
    implied = [es for es in facts.collective_events
               if es.event.kind in ("psum", "allgather")]
    if not implied:
        return
    # the symbolic sequence: every collective-like event in walk
    # order, tagged with whether ALL coordinates observe it
    literal = {id(site.op): site for site in facts.sites
               if _is_collective(site.op)}
    seq = []
    for site in facts.sites:
        if id(site.op) in literal:
            g = facts.guards(site.op)
            seq.append((f"{site.op.type}@{site.anchor()}",
                        facts.divergent(g) or facts.unproven(g)))
    for es in implied:
        seq.append((f"implied-{es.event.kind}"
                    f"[{','.join(sorted(set(es.event.axes)))}]"
                    f"@{es.site.anchor()}",
                    facts.divergent(es.guards)
                    or facts.unproven(es.guards)))
    for es in implied:
        if not es.guards or not facts.unproven(es.guards):
            continue  # unguarded / value-uniform: every coord agrees
        divergent = facts.divergent(es.guards)
        sev = ERROR if divergent else WARNING
        srcs = sorted({g.source for g in es.guards
                       if g.fact == absint.VARYING and g.source})
        all_seq = ", ".join(s for s, _ in seq)
        other_seq = ", ".join(s for s, guarded in seq
                              if not guarded) or "(empty)"
        yield _diag_at(
            "PTA161", sev, es.site,
            f"collective-order disagreement: the sharded lowering "
            f"implies a {_event_where(es)} under "
            f"{len(es.guards)} traced guard(s) "
            f"[{_guard_proof(facts, es.guards)}]. "
            + (f"Coordinates where the guard holds observe the "
               f"sequence [{all_seq}]; coordinates differing in "
               f"{srcs} observe [{other_seq}] — participants "
               f"disagree on whether this collective runs: deadlock"
               if divergent else
               "divergence of the guard is unprovable, so order "
               "agreement across mesh coordinates cannot be "
               "verified"),
            var=es.event.var,
            hint="hoist the sharded computation (and its implied "
                 "collective) out of the divergent region and mask "
                 "its input instead — or keep tp-sharded params out "
                 "of per-stage/per-lane branches (the 1F1B x tp "
                 "rejection, derived)")


@register_checker("PTA170", "device-memory-budget")
def check_device_memory_budget(program: Program):
    """Static per-device memory budget: when a program opts in via
    ``absint.set_device_memory_budget(program, bytes)``, the PTA170
    planner (analysis/memplan.py — persistable + feed + temp bytes
    under the propagated ShardSpecs, validated against the XLA
    compiler's own ``compiled.memory_analysis()`` accounting in
    tests/test_memory_plan.py) prices the program per device and an
    over-budget plan becomes an ERROR here instead of a device OOM
    after minutes of compile."""
    from . import absint

    budget = absint.device_memory_budget(program)
    if budget is None:
        return
    facts = absint.analyze(program)
    plan = facts.device_memory_plan()
    total = plan.total_device_bytes
    if total <= budget:
        return
    top = sorted(plan.state + plan.feeds,
                 key=lambda v: -v.device_bytes)[:3]
    biggest = ", ".join(f"{v.name}={v.device_bytes}B" for v in top)
    yield Diagnostic(
        "PTA170", ERROR,
        f"per-device memory plan {total} bytes exceeds the declared "
        f"budget {budget} bytes (state {plan.state_device_bytes} + "
        f"feeds {plan.feed_device_bytes} + temps "
        f"{plan.temp_device_bytes}; largest: {biggest})"
        + (f" on mesh {plan.mesh.describe()}" if plan.mesh else ""),
        hint="shard the largest state over a mesh axis "
             "(absint.mark_sharded with a {dim: axis} placement), "
             "shrink the geometry, or raise the budget")


# ---------------------------------------------------------------------------
# PTA190/PTA191/PTA192: the pool ownership & lifetime prover (the
# ownership domain of analysis/absint.py — symbolic index provenance,
# per-block typestates, and the host allocator's named assumptions).
# ---------------------------------------------------------------------------
def _chain_of(fact) -> str:
    if fact is None or not fact.chain:
        return "(no provenance chain: the value never passed a "\
            "registered index rule or marked source)"
    return " ← ".join(reversed(fact.chain))


def _exclusive_tags(fact):
    from . import absint

    srcs = absint.pool_index_sources()
    return [t for t in (fact.tags if fact else ())
            if t in srcs
            and srcs[t].typestate == absint.TS_EXCLUSIVE]


def _shared_tags(fact):
    from . import absint

    srcs = absint.pool_index_sources()
    return [t for t in (fact.tags if fact else ())
            if t in srcs and srcs[t].typestate == absint.TS_SHARED]


def _gate_ok(fact) -> bool:
    from . import absint

    srcs = absint.pool_index_sources()
    return fact is not None and any(
        t in srcs and srcs[t].typestate == absint.TS_GATE
        for t in fact.tags)


@register_checker("PTA190", "pool-access-provenance")
def check_pool_access_provenance(program: Program):
    """Provenance + in-bounds prover for every ``@POOL`` access the
    ownership domain recorded (reads AND writes):

    * **provenance** — the index must chain to a registered
      host-owned source (``mark_pool_index_source``: block-table
      feeds, host-deduplicated admission targets, refcounted prompt
      refs) or be a trace-time constant (the dustbin row). An index
      of UNKNOWN provenance is an ERROR with the chain printed: a
      device-computed index nobody vouches for is exactly how a lane
      scribbles over another request's KV with no error anywhere.
    * **gate** — a write declared ``exclusive_via='block_table'``
      must be gated by the lane-active mask (a gate whose provenance
      chains to a ``lane_active``-marked source): stale table rows of
      idle/dustbin/paused lanes address blocks other lanes now own.
    * **in-bounds** — when the indexed axis extent is static, the
      index fact's bound must fit it (ERROR when the bound provably
      exceeds the axis; WARNING when no bound is derivable for a
      READ — the write kernel clamps out-of-range rows into its
      trash row, reads have no such net)."""
    from . import absint

    facts = absint.analyze(program)
    if not facts.converged:
        return  # PTA110's declaration fallback owns this program
    for acc in facts.pool_accesses:
        if acc.kind == "write" and acc.index_var is None:
            continue  # direct (non-masked_pool_write) writer: PTA191
        fact = acc.index_fact
        if fact is None or (not fact.tags and not fact.const):
            yield _diag_at(
                "PTA190", ERROR, acc.site,
                f"{acc.kind} of shared pool {acc.pool!r} through "
                f"index {acc.index_var!r} of UNKNOWN provenance "
                f"[{_chain_of(fact)}]: no host-owned source vouches "
                f"for these cells", var=acc.pool,
                hint="chain the index to a marked host table "
                     "(absint.mark_pool_index_source) through "
                     "registered index rules "
                     "(analysis/ownership_rules.py), or feed "
                     "host-deduplicated indices")
            continue
        if acc.kind == "write" and acc.gate_var is not None and \
                acc.site.op.attrs.get("exclusive_via") \
                == "block_table" and not _gate_ok(acc.gate_fact):
            # a write with NO Gate input at all is PTA191's finding
            # (one incident, one diagnostic); this judges only the
            # provenance of a gate that exists
            yield _diag_at(
                "PTA190", ERROR, acc.site,
                f"block-table write into {acc.pool!r} is not gated "
                f"by the lane-active mask (gate {acc.gate_var!r}: "
                f"{_chain_of(acc.gate_fact)}): idle/dustbin/paused "
                f"lanes would scatter through stale table rows into "
                f"blocks other lanes own", var=acc.pool,
                hint="gate with the active mask "
                     "(absint.mark_pool_index_source(active, "
                     "'lane_active'); gate=cast(active,'float32'))")
        if acc.axis_size is not None:
            if fact.bound is not None and fact.bound > acc.axis_size:
                yield _diag_at(
                    "PTA190", ERROR, acc.site,
                    f"{acc.kind} of pool {acc.pool!r}: index bound "
                    f"{fact.bound} exceeds the indexed axis extent "
                    f"{acc.axis_size} [{_chain_of(fact)}]",
                    var=acc.pool,
                    hint="fix the mint-site bound "
                         "(mark_pool_index_source(..., bound=N)) or "
                         "the addressing arithmetic")
            elif fact.bound is None and acc.kind == "read" \
                    and not fact.const:
                yield _diag_at(
                    "PTA190", WARNING, acc.site,
                    f"read of pool {acc.pool!r}: in-bounds is "
                    f"unprovable (no bound derivable for index "
                    f"{acc.index_var!r} [{_chain_of(fact)}]); a "
                    f"gather past the pool end returns clamped "
                    f"garbage silently", var=acc.pool,
                    hint="declare the host invariant's bound at the "
                         "mint site: mark_pool_index_source(var, "
                         "tag, bound=N)")


@register_checker("PTA191", "pool-write-exclusive-proven")
def check_pool_write_exclusive_proven(program: Program):
    """The PROOF form of PTA110: for every shared-pool write the
    ownership domain recorded, prove distinct lanes' writes hit
    disjoint rows — GIVEN the host allocator's disjoint-allocation
    invariant as a NAMED assumption (the ownership seed table entry
    backing the index's provenance tag; property-tested in
    tests/test_block_pool_model.py). The structural PTA110 contract
    (one blessed writer op, read-modify-write, a declared
    ``exclusive_via``, a Gate on block-table writes) is re-enforced
    here so the twin-dedupe loses nothing, and the declaration is
    UPGRADED: ``exclusive_via`` must AGREE with the provenance the
    prover actually derived — a builder declaring 'block_table'
    while wiring host-admission indices (or vice versa) claims an
    invariant nobody is maintaining. Indices mixing two exclusive
    source families are rejected: each family's disjointness is
    per-family; their union proves nothing."""
    from . import absint

    facts = absint.analyze(program)
    if not facts.converged:
        return  # PTA110's declaration fallback owns this program
    srcs = absint.pool_index_sources()
    for acc in facts.pool_accesses:
        if acc.kind != "write":
            continue
        op = acc.site.op
        name = acc.pool
        if op.type != "masked_pool_write":
            yield _diag_at(
                "PTA191", ERROR, acc.site,
                f"op {op.type!r} writes shared block pool {name!r} "
                f"directly; only masked_pool_write's disjoint "
                f"one-hot scatter is provably lane-exclusive — "
                f"anything else is the silent cross-request KV "
                f"corruption class", var=name,
                hint="route the write through layers.masked_pool_"
                     "write(pool, new, index, gate, "
                     "exclusive_via=...)")
            continue
        if name not in op.input_arg_names:
            yield _diag_at(
                "PTA191", ERROR, acc.site,
                f"masked_pool_write writes {name!r} without reading "
                f"it: the keep-mask read-modify-write is what "
                f"preserves other lanes' cells (and keeps the pool "
                f"on the state_in path — see PTA090)", var=name)
            continue
        via = op.attrs.get("exclusive_via")
        if via not in _POOL_EXCLUSIVE_VIA:
            yield _diag_at(
                "PTA191", ERROR, acc.site,
                f"masked_pool_write into {name!r} carries "
                f"exclusive_via={via!r}; the builder must name the "
                f"exclusivity assumption "
                f"({'/'.join(_POOL_EXCLUSIVE_VIA)})", var=name)
            continue
        if via == "block_table" and not op.inputs.get("Gate"):
            yield _diag_at(
                "PTA191", ERROR, acc.site,
                f"block-table write into {name!r} has no Gate input: "
                f"idle/dustbin/paused lanes (active=0) would scatter "
                f"through stale table rows into blocks other lanes "
                f"own", var=name,
                hint="pass gate=cast(active, 'float32')")
            continue
        fact = acc.index_fact
        if fact is None or (not fact.tags and not fact.const):
            continue  # unknown provenance: PTA190's finding
        excl = sorted(set(_exclusive_tags(fact)))
        if len(excl) > 1:
            yield _diag_at(
                "PTA191", ERROR, acc.site,
                f"write into {name!r} mixes exclusive index "
                f"families {excl} [{_chain_of(fact)}]: each "
                f"family's disjointness assumption "
                f"({', '.join(srcs[t].assumption or t for t in excl)}) "
                f"is per-family — their union proves nothing",
                var=name,
                hint="derive the write index from ONE host-owned "
                     "source family")
            continue
        if excl and excl[0] != via:
            src = srcs[excl[0]]
            yield _diag_at(
                "PTA191", ERROR, acc.site,
                f"write into {name!r} declares exclusive_via="
                f"{via!r} but its index provenance chains to "
                f"{excl[0]!r} (assumption "
                f"{src.assumption or 'none'}) "
                f"[{_chain_of(fact)}]: the declaration names an "
                f"invariant nobody is maintaining for these "
                f"indices", var=name,
                hint="fix the declaration or the index wiring; the "
                     "declared via must name the assumption the "
                     "proof actually rests on")


@register_checker("PTA192", "pool-write-while-shared")
def check_pool_write_while_shared(program: Program):
    """Read-only-while-shared: the per-block lifetime lattice is
    ``free → exclusive(lane) → shared(refcount>1) → freed``, and
    WRITES are only legal in the exclusive typestate — exactly the
    copy-on-write contract the radix-tree/beam prefix-sharing work
    needs (ROADMAP), landed BEFORE the feature so COW lowerings
    build on a proven base. An index whose provenance chains to a
    REFCOUNTED source (``prompt_entry_ref``: entries shared across
    lanes with identical prompts) certifies reads only; a write
    through it would mutate KV that OTHER live lanes are attending
    to — generations stay plausible and no error ever surfaces.
    The host half of the bargain (refcount monotonicity, no
    free-while-shared, fresh entries exclusive at refcount==1) is
    the property-tested allocator state machine
    (models/decode_engine.HostBlockPool / PromptPrefixCache,
    tests/test_block_pool_model.py)."""
    from . import absint

    facts = absint.analyze(program)
    if not facts.converged:
        return  # PTA110's declaration fallback owns this program
    srcs = absint.pool_index_sources()
    for acc in facts.pool_accesses:
        if acc.kind != "write":
            continue
        shared = sorted(set(_shared_tags(acc.index_fact)))
        if not shared:
            continue
        descs = "; ".join(
            f"{t}: {srcs[t].description}" for t in shared)
        yield _diag_at(
            "PTA192", ERROR, acc.site,
            f"write into shared pool {acc.pool!r} through index "
            f"{acc.index_var!r} whose provenance chains to "
            f"REFCOUNTED (shared-typestate) source(s) {shared} "
            f"[{_chain_of(acc.index_fact)}]: writes are only legal "
            f"in the exclusive typestate (refcount==1) — this is "
            f"the write-while-shared COW violation ({descs})",
            var=acc.pool,
            hint="copy-on-write first: acquire a FRESH entry "
                 "(PromptPrefixCache.acquire_fresh, refcount==1), "
                 "write through its host-fed index "
                 "(exclusive_via='host_indices'), and repoint the "
                 "lane's ref after the copy")


# ---------------------------------------------------------------------------
# PTA140: declared shape/dtype clobbered by producer inference.
# ---------------------------------------------------------------------------
@register_checker("PTA140", "declared-shape-clobber")
def check_declared_clobbers(program: Program):
    """Build-time shape inference overwrites a var's DECLARED shape/
    dtype with the producer's inferred one, in place (the r10
    incident: assign of a [-1,4] value onto a concretely-declared
    persistable rewrites it to [-1,4] — and every contract hanging
    off the declaration, scan-carry seeding, feed validation, PTA090
    concreteness, silently moves with it). core/registry.py stashes
    the pre-clobber declaration; this checker surfaces the
    disagreements:

    * a persistable/data var declared with a CONCRETE shape whose
      producer re-inferred it differently — ERROR (the declaration
      was a contract; the producer broke it);
    * an integer-declared CONTRACT var (persistable, data, or a
      while/run_block_if carry) whose producer promoted it to float —
      the PTA020 int->float promotion generalized beyond `increment`:
      ERROR when the var is a while carry (the lax.while_loop carry
      dtype breaks), WARNING elsewhere. Arithmetic temps are exempt:
      an int scaled by a float step legitimately becomes float — only
      dtypes some contract hangs off are findings."""
    from . import absint

    clobbers = absint.declared_clobbers(program)
    if not clobbers:
        return
    carried = absint.while_carried_names(program)
    for c in clobbers:
        if c.declared_shape is not None and \
                (c.persistable or c.is_data) and \
                all(d is not None and d >= 0
                    for d in c.declared_shape):
            yield Diagnostic(
                "PTA140", ERROR,
                f"{'persistable' if c.persistable else 'data'} var "
                f"{c.name!r} was DECLARED with concrete shape "
                f"{c.declared_shape} but build-time shape inference "
                f"clobbered it to {c.final_shape} from its producer "
                f"— the declared feed/carry contract silently moved",
                block_idx=c.block_idx, var=c.name,
                hint="make the producer emit the declared shape (a "
                     "static-batch producer pins it — the PTA090 "
                     "test discipline), or declare the var with the "
                     "producer's real shape")
        if c.declared_dtype is not None and \
                _is_int_dtype_str(c.declared_dtype) and \
                c.final_dtype is not None and \
                c.final_dtype.startswith("float") and \
                (c.persistable or c.is_data or c.name in carried):
            sev = ERROR if c.name in carried else WARNING
            yield Diagnostic(
                "PTA140", sev,
                f"var {c.name!r} was DECLARED {c.declared_dtype} but "
                f"its producer promoted it to {c.final_dtype}"
                + (" and it is a while-loop carry: the "
                   "lax.while_loop carry dtype breaks (PTA020 "
                   "generalized)" if sev == ERROR else
                   " (PTA020's int->float promotion, generalized "
                   "beyond `increment`)"),
                block_idx=c.block_idx, var=c.name,
                hint="keep integer state integer: int steps, int "
                     "fill_constants, explicit casts at the float "
                     "boundary")


# ---------------------------------------------------------------------------
# PTA201/PTA202: the liveness domain's program-level provers
# (analysis/liveness.py; PTA200's capacity model is bundle-level and
# lives in check_bundle below).
# ---------------------------------------------------------------------------
@register_checker("PTA200", "admission-capacity-feasibility")
def check_admission_capacity(program: Program):
    """Admission-capacity feasibility: the serving configuration's
    worst-case steady-state resource demand must fit its static
    pools, or admission can wedge forever with no error anywhere.
    Two pools are modeled (analysis/liveness.py): ``HostBlockPool``
    (demand = n_slots lanes x pages(max_out_len) blocks, assuming no
    radix sharing) and ``PromptPrefixCache`` (demand = the declared
    workload's distinct SESSION prompts, which pin one entry each for
    the session lifetime, plus one churn entry when cold traffic
    shares the cache). The deadlock witness is validated against the
    exhaustive bounded explorer in analysis/protomodel.py
    (session_protocol), so "INFEASIBLE" comes with a replayable
    minimal trace, and the serving layer raises the same verdict as
    ``AdmissionInfeasible`` at submit time.

    This checker is BUNDLE-level: the capacity model reads the
    bundle's static shape (n_slots/max_out_len/cache) and declared
    ``workload``, not any one program's IR, so the check runs in
    ``check_bundle`` and this program-level registration exists for
    the catalog/--explain surface.

    Example::

        bundle.workload = {"distinct_session_prompts": 5}
        # cache.n_prompt_entries == 3, sessions never close:
        # every admitted session pins an entry forever; after 3
        # admissions all entries are pinned and unevictable, the
        # 4th distinct prompt waits forever -> PTA200 error

    Suppress with a bundle-level attr
    ``bundle._pta_suppress = (("PTA200", "reason"),)`` — counted in
    the CI baseline's suppressed section, never silent."""
    return ()


@register_checker("PTA201", "release-on-every-exit-path")
def check_release_obligations(program: Program):
    """Every acquire obligation this program exercises must be
    discharged on EVERY declared protocol exit path. An ownership tag
    reaching a ``@POOL`` access names a resource hold (HostBlockPool
    block, PromptPrefixCache entry, radix incref); its
    ``AcquireContract`` (absint.register_acquire_release) declares
    the exit paths — retirement, preemption, abort, invalidate,
    session close, server close, handoff — and the serving layer
    registers the release SITE proving each one
    (absint.register_release_site at the method that implements it).
    A tag with no contract, or a declared exit with no site, is an
    ERROR: an undischarged hold on a rare exit path is exactly how a
    pool drains one leaked block per preemption until admission
    wedges with no error anywhere.

    Example::

        # a builder minting a NEW resource-holding index source
        absint.register_pool_index_source("my_tab", "...",
                                          absint.TS_EXCLUSIVE)
        absint.mark_pool_index_source(tab, "my_tab", bound=N)
        # ...without ALSO registering its liveness contract:
        #   absint.register_acquire_release("my_tab",
        #       acquire="MyPool.alloc", release="MyPool.decref",
        #       exits=("retire", "preempt"), resource="MyPool")
        # and a release site per exit (from the code implementing
        # it):
        #   absint.register_release_site("my_tab", "retire",
        #       "MyServer._free_lane_locked")
        # -> PTA201 error at the first @POOL access the tag reaches

    Suppress with ``_pta_suppress=("PTA201", "why this hold is
    deliberately leaked")`` on the mint-site/access op — counted in
    the CI baseline, never silent."""
    from . import absint, liveness

    facts = absint.analyze(program)
    if not facts.converged:
        return
    ledger = liveness.obligation_ledger(facts)
    if not ledger["unproven"]:
        return
    # anchor each tag's findings at its first pool access so the
    # counted _pta_suppress convention (op-anchored) applies
    anchor_of: Dict[str, OpSite] = {}
    for acc in facts.pool_accesses:
        fact = acc.index_fact
        for t in (fact.tags if fact is not None else ()):
            anchor_of.setdefault(t, acc.site)
    for item in ledger["unproven"]:
        tag = item.split(":", 1)[0]
        site = anchor_of.get(tag)
        msg = (f"unproven release obligation — {item}: a hold with "
               f"an unproven discharge path leaks pool capacity on "
               f"that path until admission wedges")
        hint = ("register the contract/site: absint."
                "register_acquire_release(tag, acquire, release, "
                "exits, resource) beside the mint site, absint."
                "register_release_site(tag, exit, 'Class.method') "
                "from the code that releases")
        if site is not None:
            yield _diag_at("PTA201", ERROR, site, msg, hint=hint)
        else:
            yield Diagnostic("PTA201", ERROR, msg, hint=hint)


@register_checker("PTA202", "while-variant-progress")
def check_while_progress(program: Program):
    """Every While loop must carry a SOUND termination variant
    instead of being trusted by construction: the condition's
    backward slice through the body must contain a positive-step
    ``increment`` counter AND a loop-invariant bound terminal (a data
    feed, a ``fill_constant``, or a parent-block value the body
    cannot write). Serve/burst Whiles (condition producer marked
    ``lane_active_mask``) are held to ERROR — their burst-exit
    disjunct additionally rides the NAMED monotone-mask assumption
    (active lanes only retire within a burst), so the counter term
    alone must bound the loop; other unproven Whiles are WARNING (a
    legal data-dependent loop could still terminate, but nothing
    here proves it).

    Example::

        cond = layers.less_than(counter, limit, cond=cond)  # in body
        # ...with NO layers.increment(counter, 1) in the body:
        # the slice has a bound but no counter -> PTA202 (and a
        # serve While whose body never recomputes its condition at
        # all can only spin -> PTA202 error)

    Suppress with ``_pta_suppress=("PTA202", "reason")`` on the
    while op — counted, never silent."""
    from . import liveness

    for v in liveness.while_variants(program):
        if v.proven:
            continue
        sev = ERROR if v.kind == "serve" else WARNING
        msg = (f"While has no provable termination variant "
               f"({v.detail}); "
               + ("this is a serve/burst loop — an unbounded burst "
                  "holds the dispatch hostage and never returns "
                  "lane results" if v.kind == "serve" else
                  "nothing proves this loop makes progress"))
        hint = ("drive the condition from an increment-stepped "
                "counter compared against a fed/const limit, "
                "recomputed in the body (the decode_engine "
                "_serve_cond pattern)")
        if v.site is not None:
            yield _diag_at("PTA202", sev, v.site, msg, hint=hint)
        else:
            yield Diagnostic("PTA202", sev, msg, hint=hint)


# ---------------------------------------------------------------------------
# PTA150: whole-bundle contracts (DecodeStepBundle as ONE lint unit).
# ---------------------------------------------------------------------------
def _bundle_programs(bundle):
    """(label, program) for every program a DecodeStepBundle ships.
    Duck-typed: analysis stays IR-level and never imports
    models/decode_engine."""
    out = []
    for a, p in sorted(getattr(bundle, "prefills", {}).items()):
        out.append((f"prefill{a}", p))
    for a, p in sorted(getattr(bundle, "hit_prefills", {}).items()):
        out.append((f"hit_prefill{a}", p))
    step = getattr(bundle, "step", None)
    if step is not None:
        out.append(("step", step))
    for key, p in sorted(getattr(bundle, "serves", {}).items(),
                         key=lambda kv: str(kv[0])):
        out.append((f"serve{key}", p))
    return out


def _persistable_decls(program):
    """name -> (shape, dtype) as the BUILDER declared it: the stashed
    pre-clobber declaration (core/registry.py) beats the final
    inferred metadata — e.g. with x64 disabled, inference
    canonicalizes a declared int64 persistable to int32 on every
    program identically, which is not a bundle disagreement."""
    decls = {}
    for blk, _ in iter_blocks(program):
        for name, var in blk.vars.items():
            if not var.persistable or name in decls:
                continue
            shape = getattr(var, "_declared_shape", None)
            if shape is None:
                shape = tuple(var.shape) if var.shape is not None \
                    else None
            dtype = getattr(var, "_declared_dtype", None) or var.dtype
            decls[name] = (shape,
                           dtype.value if dtype is not None else None)
    return decls


def check_bundle(bundle,
                 collect_suppressed: Optional[list] = None
                 ) -> List[Diagnostic]:
    """PTA150 + PTA200: lint a whole DecodeStepBundle as ONE unit.
    The bundle's
    programs are SPECIALIZATIONS over shared scope state — one
    admission flavor per bucket, a standalone step, the fused serves —
    and the serving layer dispatches them interchangeably against the
    same scope, so they must agree on:

    * **cache geometry** — every slot-state var (`_state_specs`) and
      every shared persistable must be declared with IDENTICAL
      shape/dtype in every program that touches it: a serve
      specialization disagreeing with the step program corrupts the
      scope the other programs read (today only pairwise
      `pair_check`s existed; this is the n-way sweep);
    * **counter presence** — the bundle's `state` vars (token buffer,
      step/finished/active masks, spec counters) must be declared in
      the step program and every serve: a specialization missing one
      silently decodes against stale state;
    * **seed derivation** — every sampling/acceptance op that carries
      a `base_seed` attr must carry the SAME value across all
      specializations: the r14 replay contract keys noise purely on
      (base_seed, request seed, position), so a serve specialization
      with a drifted base_seed emits different tokens for the same
      request depending on which program the scheduler happened to
      dispatch;
    * **admission-capacity feasibility** (PTA200, the liveness
      domain): the bundle's static shape must admit a live steady
      state — lane block chains must fit ``HostBlockPool`` and the
      declared session workload's pinned prompts must fit
      ``PromptPrefixCache`` (analysis/liveness.py; the protomodel
      explorer is the oracle). Bundle-level diagnostics have no op
      anchor, so a deliberate witness target suppresses via a
      ``_pta_suppress`` attr ON THE BUNDLE object — counted through
      `collect_suppressed` exactly like op-anchored ones.

    Example (PTA200)::

        bundle.workload = {"distinct_session_prompts": 5}
        # with cache.n_prompt_entries == 3 and sessions that never
        # close: 5 pinned entries can never fit 3 slots -> PTA200
        # error with the session-pinning deadlock witness

    Reference counterpart: op_desc.cc validates ONE program; the
    bundle gate is the capability the whole-block-jit serving path
    needs instead."""
    out: List[Diagnostic] = []
    progs = _bundle_programs(bundle)
    if not progs:
        return out
    specs = dict(getattr(bundle, "_state_specs", {}) or {})
    state = dict(getattr(bundle, "state", {}) or {})

    decls_by_prog = {label: _persistable_decls(p)
                     for label, p in progs}

    # cache geometry: spec agreement + n-way cross-program agreement
    for name, (shape, dt) in sorted(specs.items()):
        want = (tuple(shape), str(np_dtype_name(dt)))
        for label, decls in decls_by_prog.items():
            got = decls.get(name)
            if got is None:
                continue
            got_n = (got[0], np_dtype_name(got[1])
                     if got[1] is not None else None)
            if got_n != want:
                out.append(Diagnostic(
                    "PTA150", ERROR,
                    f"bundle program {label!r} declares slot-state "
                    f"var {name!r} as {got_n} but the bundle's state "
                    f"spec says {want}: the specializations share "
                    f"ONE scope — a geometry disagreement corrupts "
                    f"it", var=name,
                    hint="every specialization must declare slot "
                         "state from the same _slot_state_specs "
                         "table"))
    seen: Dict[str, tuple] = {}
    for label, decls in sorted(decls_by_prog.items()):
        for name, got in sorted(decls.items()):
            if name in specs:
                continue  # already checked against the spec table
            prev = seen.get(name)
            if prev is None:
                seen[name] = (label, got)
            elif prev[1] != got and None not in (prev[1][0], got[0]):
                out.append(Diagnostic(
                    "PTA150", ERROR,
                    f"bundle programs {prev[0]!r} and {label!r} "
                    f"declare shared persistable {name!r} with "
                    f"different shape/dtype ({prev[1]} vs {got}): "
                    f"one scope serves both", var=name))

    # counter presence
    must_have = [(label, p) for label, p in progs
                 if label == "step" or label.startswith("serve")]
    for logical, name in sorted(state.items()):
        for label, _p in must_have:
            if name not in decls_by_prog[label]:
                out.append(Diagnostic(
                    "PTA150", ERROR,
                    f"bundle program {label!r} does not declare the "
                    f"bundle state var {name!r} (logical "
                    f"{logical!r}): it would decode against stale "
                    f"or missing scope state", var=name))

    # seed derivation
    base_seeds: Dict[str, Dict[object, str]] = {}
    for label, p in progs:
        for site in iter_ops(p):
            bs = site.op.attrs.get("base_seed")
            if bs is None:
                continue
            base_seeds.setdefault(site.op.type, {}).setdefault(
                bs, label)
    for op_type, values in sorted(base_seeds.items()):
        if len(values) > 1:
            detail = ", ".join(
                f"{v!r} (first in {label!r})"
                for v, label in sorted(values.items(),
                                       key=lambda kv: str(kv[0])))
            out.append(Diagnostic(
                "PTA150", ERROR,
                f"bundle specializations disagree on {op_type!r} "
                f"base_seed: {detail} — the same logical draw must "
                f"be byte-identical in every specialization (the "
                f"r14 replay contract), so one bundle has ONE "
                f"base_seed",
                hint="derive every specialization's sampling ops "
                     "from the bundle's single SamplingConfig/"
                     "DraftConfig base_seed"))

    # PTA200: admission-capacity feasibility (bundle-level — the
    # capacity model is a property of the bundle's static shape +
    # declared workload, not of any one program)
    from . import liveness as _liveness

    suppress: Dict[str, str] = {}
    raw = getattr(bundle, SUPPRESS_ATTR, None)
    if raw is not None:
        entries = _normalize_suppressions(raw)
        if entries is None:
            out.append(Diagnostic(
                "PTA199", WARNING,
                f"malformed bundle-level {SUPPRESS_ATTR} attr "
                f"{raw!r}; expected (\"PTA0xx\", \"reason\") or a "
                f"list of such pairs — the suppression is IGNORED"))
        else:
            suppress = dict(entries)
    for chk in _liveness.bundle_capacity_checks(bundle):
        if chk.feasible:
            continue
        d = Diagnostic(
            "PTA200", ERROR,
            f"admission-capacity INFEASIBLE for {chk.resource}: "
            f"{chk.witness}", var=chk.resource,
            hint="grow the pool (n_blocks/n_prompt_entries), shrink "
                 "the workload's distinct session prompts, or let "
                 "sessions close (close_session releases the pin); "
                 "serving preflights raise AdmissionInfeasible on "
                 "this config before any request wedges")
        reason = suppress.get("PTA200")
        if reason is not None:
            if collect_suppressed is not None:
                collect_suppressed.append((d, reason))
            continue
        out.append(d)
    return out


def np_dtype_name(dt) -> str:
    """Canonical dtype string for bundle-spec comparison ('int64',
    'float32', ...): accepts numpy dtypes/strings/DataType values.
    Reference counterpart: framework/data_type.h ToDataType's
    proto-enum canonicalization, reduced to numpy names."""
    import numpy as np

    try:
        return np.dtype(dt).name
    except TypeError:
        return str(getattr(dt, "value", dt))
