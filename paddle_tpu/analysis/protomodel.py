"""Exhaustive bounded model checking of the host allocator protocol.

The serving layer's correctness story so far rests on three legs:
typestate machines that RAISE on bad transitions (``HostBlockPool``/
``PromptPrefixCache``/``RadixBlockTree`` — ``BlockLifetimeError``),
randomized property traces over them (tests/test_block_pool_model.py),
and the static ownership prover (PTA190-192). None of those is a
LIVENESS argument: a protocol can pass every random trace and still
have an interleaving that wedges admissions forever (the session-pin
deadlock CLAUDE.md documents in prose) or leaks a refcount on one rare
exit path. This module closes that gap with the smallest tool that
actually proves something: an exhaustive breadth-first explorer over
the REAL allocator classes at small bounds. Every reachable
interleaving of a modeled protocol is visited; invariants are checked
in every state; a drain obligation ("after everyone retires, the pool
is all-free") is checked from every state; and because the search is
BFS, the first violation found carries a MINIMAL action trace — a
counterexample a human can replay by hand.

This is the oracle the PTA200 admission-capacity model (analysis/
liveness.py) is validated against: the declarative feasibility
predicate and the explorer must agree on every small configuration
(tests/test_protomodel.py runs the cross-validation grid), which is
what licenses the static checker to claim "provably infeasible"
without enumerating states at lint time.

Design notes:

* States drive the REAL classes from models/decode_engine.py (lazy
  imports inside the builders keep this module importable without the
  models package — the analysis-never-imports-models discipline holds
  at module level). A seeded bug in an allocator therefore fails HERE,
  not just in a hand-written abstraction of it.
* ``fingerprint`` canonicalizes allocator internals INCLUDING
  free-list order (alloc pops from the tail, so order is semantics).
* Violation kinds: ``invariant`` (a state predicate failed),
  ``lifetime`` (the allocator itself raised ``BlockLifetimeError`` —
  the typestate machine caught a protocol bug), ``deadlock`` (work
  outstanding, no action enabled), ``leak`` (the drain obligation
  failed: retiring everything did not return the pool to all-free).

Reference counterpart: none — the reference framework's allocator
checks are runtime asserts (reference paddle/fluid/framework/scope.cc,
memory/ allocators); an exhaustive protocol-state explorer is the
de-risking capability the shared-pool serving era needs instead.
"""
from __future__ import annotations

import copy
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "Action", "Violation", "Result", "Protocol", "explore",
    "pool_fingerprint", "cache_fingerprint", "tree_fingerprint",
    "block_pool_protocol", "prefix_cache_protocol", "radix_protocol",
    "session_protocol", "session_feasible",
]


def _lifetime_error():
    from ..models.decode_engine import BlockLifetimeError

    return BlockLifetimeError


@dataclass(frozen=True)
class Action:
    """One protocol move: enabled iff ``guard(state)``; ``effect``
    mutates the state in place (the explorer deep-copies first).
    Reference counterpart: none (module docstring)."""
    name: str
    guard: Callable[[dict], bool]
    effect: Callable[[dict], None]


@dataclass(frozen=True)
class Violation:
    """A counterexample: the MINIMAL (BFS) action trace reaching it.
    Reference counterpart: none (module docstring)."""
    kind: str                  # invariant | lifetime | deadlock | leak
    trace: Tuple[str, ...]
    detail: str

    def format(self) -> str:
        steps = " -> ".join(self.trace) if self.trace else "(initial)"
        return f"[{self.kind}] after {steps}: {self.detail}"


@dataclass(frozen=True)
class Result:
    """Outcome of one bounded exploration. ``ok`` means every reached
    state satisfied every obligation AND the search was exhaustive
    within ``max_states`` (``truncated`` reports a hit bound — a
    truncated green run is a weaker claim and tests must assert
    ``not truncated``). Reference counterpart: none."""
    ok: bool
    n_states: int
    n_transitions: int
    truncated: bool
    counterexample: Optional[Violation]


@dataclass
class Protocol:
    """A bounded protocol machine: initial-state factory, action
    alphabet, state invariants (name, fn -> None-or-detail), a
    canonicalizing fingerprint, an ``accepting`` predicate (states
    where having NO enabled action is fine — omitted means every
    stuck state is acceptable, i.e. pure safety checking), and an
    optional ``drain`` obligation run on a COPY of every state (return
    a detail string when the everything-retires unwinding leaks).
    Deliberately mutable so tests can swap one action's effect to
    seed a bug (the dropped-decref mutation test).
    Reference counterpart: none (module docstring)."""
    name: str
    make_init: Callable[[], dict]
    actions: List[Action]
    invariants: List[Tuple[str, Callable[[dict], Optional[str]]]] = \
        field(default_factory=list)
    fingerprint: Callable[[dict], object] = lambda s: repr(s)
    accepting: Optional[Callable[[dict], bool]] = None
    drain: Optional[Callable[[dict], Optional[str]]] = None


def explore(proto: Protocol, max_states: int = 20000) -> Result:
    """Exhaustive BFS over ``proto``'s reachable states (up to
    ``max_states`` distinct fingerprints). Checks every invariant and
    the drain obligation in every newly discovered state, runs every
    enabled action from every state (catching ``BlockLifetimeError``
    as a lifetime violation), and flags deadlock on non-accepting
    stuck states. BFS guarantees the returned counterexample trace is
    minimal in action count. Reference counterpart: none."""
    LifetimeError = _lifetime_error()
    n_transitions = 0
    truncated = False

    def check(state, trace) -> Optional[Violation]:
        for name, inv in proto.invariants:
            detail = inv(state)
            if detail:
                return Violation("invariant", trace,
                                 f"{name}: {detail}")
        if proto.drain is not None:
            try:
                detail = proto.drain(copy.deepcopy(state))
            except LifetimeError as e:
                return Violation("lifetime", trace,
                                 f"drain raised: {e}")
            if detail:
                return Violation("leak", trace, detail)
        return None

    def result(n_states, violation):
        return Result(violation is None and not truncated, n_states,
                      n_transitions, truncated, violation)

    init = proto.make_init()
    seen = {proto.fingerprint(init)}
    queue = deque([(init, ())])
    n_states = 1
    v = check(init, ())
    if v is not None:
        return result(n_states, v)
    while queue:
        state, trace = queue.popleft()
        enabled = [a for a in proto.actions if a.guard(state)]
        if not enabled:
            if proto.accepting is not None \
                    and not proto.accepting(state):
                return result(n_states, Violation(
                    "deadlock", trace,
                    f"{proto.name}: work outstanding but no action "
                    f"enabled"))
            continue
        for a in enabled:
            nxt = copy.deepcopy(state)
            try:
                a.effect(nxt)
            except LifetimeError as e:
                return result(n_states, Violation(
                    "lifetime", trace + (a.name,), str(e)))
            n_transitions += 1
            key = proto.fingerprint(nxt)
            if key in seen:
                continue
            if n_states >= max_states:
                truncated = True
                continue
            seen.add(key)
            n_states += 1
            v = check(nxt, trace + (a.name,))
            if v is not None:
                return result(n_states, v)
            queue.append((nxt, trace + (a.name,)))
    return result(n_states, None)


# ---------------------------------------------------------------------------
# Canonical fingerprints (free-list ORDER is semantics: alloc pops the
# tail, so two states differing only in list order can diverge later).
# ---------------------------------------------------------------------------
def pool_fingerprint(pool) -> tuple:
    """Canonical tuple of a ``HostBlockPool``'s full internal state.
    Reference counterpart: none (module docstring)."""
    return (tuple(pool._free), tuple(pool._state), tuple(pool._refs))


def cache_fingerprint(cache) -> tuple:
    """Canonical tuple of a ``PromptPrefixCache``'s full internal
    state (LRU insertion order included — eviction order is
    semantics). Reference counterpart: none."""
    return (tuple(cache._free),
            tuple(sorted(cache._by_prompt.items())),
            tuple(sorted((e, r) for e, r in cache._refs.items())),
            tuple(cache._lru),
            tuple(sorted(cache._heads.items())))


def tree_fingerprint(tree) -> tuple:
    """Canonical tuple of a ``RadixBlockTree``'s node structure.
    Reference counterpart: none."""
    def node_fp(n):
        return (n.chunk, n.block,
                tuple(sorted((k, node_fp(c))
                             for k, c in n.children.items())))

    return tuple(sorted((k, node_fp(r))
                        for k, r in tree._roots.items()))


def _conservation(pool, holds: Dict[int, int]) -> Optional[str]:
    """Refcount conservation vs an explicit hold count per block, plus
    free-list/typestate consistency."""
    for b in range(pool.n_blocks):
        want = holds.get(b, 0)
        if pool._refs[b] != want:
            return (f"block {b}: refcount {pool._refs[b]} != "
                    f"{want} tracked holds")
        st = pool._state[b]
        if (st == "free") != (pool._refs[b] == 0):
            return f"block {b}: typestate {st!r} at refcount " \
                   f"{pool._refs[b]}"
    if sorted(pool._free) != sorted(
            b for b in range(pool.n_blocks) if pool._refs[b] == 0):
        return f"free list {pool._free} disagrees with refcounts"
    if len(set(pool._free)) != len(pool._free):
        return f"free list {pool._free} has duplicates"
    return None


# ---------------------------------------------------------------------------
# Protocol builders over the real allocator classes.
# ---------------------------------------------------------------------------
def block_pool_protocol(n_blocks: int = 2, n_lanes: int = 2,
                        pages: int = 1) -> Protocol:
    """Lanes alloc exclusive chains (up to ``pages`` blocks), adopt
    each other's live blocks read-only (incref — the radix-share
    shape), drop shares, and retire (decref everything — the
    ``_free_lane_locked`` unwinding). Invariant: pool refcounts ==
    tracked holds; drain: after every lane retires the pool is
    all-free. Reference counterpart: none (module docstring)."""
    from ..models.decode_engine import HostBlockPool

    def make_init():
        return {"pool": HostBlockPool(n_blocks),
                "lanes": [{"blocks": [], "shared": []}
                          for _ in range(n_lanes)]}

    def retire(lane, pool):
        for b in reversed(lane["shared"]):
            pool.decref(b)
        for b in reversed(lane["blocks"]):
            pool.decref(b)
        lane["blocks"], lane["shared"] = [], []

    def cancel(lane, pool):
        # the r20 cancel/deadline exit (_cancel_lane_locked): a
        # SEPARATE closure with the same unwinding as retirement —
        # kept distinct so the dropped-decref-on-cancel mutation
        # test can seed a bug in THIS path alone and the explorer
        # names `cancel[i]` in the minimal counterexample trace
        for b in reversed(lane["shared"]):
            pool.decref(b)
        for b in reversed(lane["blocks"]):
            pool.decref(b)
        lane["blocks"], lane["shared"] = [], []

    actions: List[Action] = []
    for li in range(n_lanes):
        def alloc(s, li=li):
            lane = s["lanes"][li]
            lane["blocks"].append(s["pool"].alloc())

        actions.append(Action(
            f"alloc[{li}]",
            lambda s, li=li: (len(s["lanes"][li]["blocks"]) < pages
                              and s["pool"].free_count > 0),
            alloc))
        for b in range(n_blocks):
            def adopt(s, li=li, b=b):
                s["pool"].incref(b)
                s["lanes"][li]["shared"].append(b)

            actions.append(Action(
                f"adopt[{li},{b}]",
                lambda s, li=li, b=b: (
                    s["pool"].refcount(b) >= 1
                    and b not in s["lanes"][li]["shared"]
                    and b not in s["lanes"][li]["blocks"]),
                adopt))

        def drop(s, li=li):
            lane = s["lanes"][li]
            s["pool"].decref(lane["shared"].pop())

        actions.append(Action(
            f"drop[{li}]",
            lambda s, li=li: bool(s["lanes"][li]["shared"]),
            drop))

        def do_retire(s, li=li):
            retire(s["lanes"][li], s["pool"])

        actions.append(Action(
            f"retire[{li}]",
            lambda s, li=li: bool(s["lanes"][li]["blocks"]
                                  or s["lanes"][li]["shared"]),
            do_retire))

        def do_cancel(s, li=li):
            cancel(s["lanes"][li], s["pool"])

        actions.append(Action(
            f"cancel[{li}]",
            lambda s, li=li: bool(s["lanes"][li]["blocks"]
                                  or s["lanes"][li]["shared"]),
            do_cancel))

    def holds_of(s):
        holds: Dict[int, int] = {}
        for lane in s["lanes"]:
            for b in lane["blocks"]:
                holds[b] = holds.get(b, 0) + 1
            for b in lane["shared"]:
                holds[b] = holds.get(b, 0) + 1
        return holds

    def conserve(s):
        return _conservation(s["pool"], holds_of(s))

    def drain(s):
        for lane in s["lanes"]:
            retire(lane, s["pool"])
        if s["pool"].free_count != n_blocks:
            return (f"after full retirement {s['pool'].free_count}/"
                    f"{n_blocks} blocks free: "
                    f"{n_blocks - s['pool'].free_count} leaked")
        return None

    return Protocol(
        name=f"block_pool(n={n_blocks},lanes={n_lanes},pages={pages})",
        make_init=make_init, actions=actions,
        invariants=[("refcount-conservation", conserve)],
        fingerprint=lambda s: (
            pool_fingerprint(s["pool"]),
            tuple((tuple(l["blocks"]), tuple(l["shared"]))
                  for l in s["lanes"])),
        drain=drain)


def _cache_can_acquire(cache, prompt) -> bool:
    """Acquire succeeds iff hit, a free slot, or an unpinned mapped
    entry to evict (mirrors ``acquire_fresh``'s None contract)."""
    kind, _ = cache.lookup(prompt)
    if kind == "hit":
        return True
    if cache._free:
        return True
    return any(cache._refs.get(cache._by_prompt[p], 0) == 0
               for p in cache._lru)


def _cache_acquire(cache, prompt) -> int:
    kind, _ = cache.lookup(prompt)
    if kind == "hit":
        return cache.acquire_hit(prompt)
    entry = cache.acquire_fresh(prompt, partial=(kind == "partial"))
    assert entry is not None, "guard must ensure acquirability"
    return entry


def prefix_cache_protocol(n_entries: int = 1, n_prompts: int = 2,
                          n_clients: int = 2,
                          with_abort: bool = True) -> Protocol:
    """Clients acquire prompt entries (hit/fresh/evict — the admission
    path), release them (retirement), and — when ``with_abort`` —
    invalidate unpinned entries (the abandoned-prefill abort path).
    Invariant: per-entry refcount == client holds and slot
    conservation; drain: release everything, invalidate every mapped
    entry, free list must be full. Reference counterpart: none."""
    from ..models.decode_engine import PromptPrefixCache

    prompts = [(i,) for i in range(n_prompts)]

    def make_init():
        return {"cache": PromptPrefixCache(n_entries, 1),
                "clients": [None] * n_clients}

    actions: List[Action] = []
    for ci in range(n_clients):
        for p in prompts:
            def acquire(s, ci=ci, p=p):
                s["clients"][ci] = _cache_acquire(s["cache"], p)

            actions.append(Action(
                f"acquire[{ci},{p[0]}]",
                lambda s, ci=ci, p=p: (
                    s["clients"][ci] is None
                    and _cache_can_acquire(s["cache"], p)),
                acquire))

        def release(s, ci=ci):
            s["cache"].release(s["clients"][ci])
            s["clients"][ci] = None

        actions.append(Action(
            f"release[{ci}]",
            lambda s, ci=ci: s["clients"][ci] is not None,
            release))

        def cancel(s, ci=ci):
            # r20 cancel exit: a torn-down holder drops its entry
            # ref exactly like retirement (separate closure for the
            # mutation test — see block_pool_protocol)
            s["cache"].release(s["clients"][ci])
            s["clients"][ci] = None

        actions.append(Action(
            f"cancel[{ci}]",
            lambda s, ci=ci: s["clients"][ci] is not None,
            cancel))
    if with_abort:
        for p in prompts:
            def invalidate(s, p=p):
                s["cache"].invalidate(s["cache"]._by_prompt[p])

            actions.append(Action(
                f"invalidate[{p[0]}]",
                lambda s, p=p: (
                    p in s["cache"]._by_prompt
                    and s["cache"].refcount(
                        s["cache"]._by_prompt[p]) == 0),
                invalidate))

    def conserve(s):
        cache = s["cache"]
        holds: Dict[int, int] = {}
        for e in s["clients"]:
            if e is not None:
                holds[e] = holds.get(e, 0) + 1
        for e in range(n_entries):
            if cache.refcount(e) != holds.get(e, 0):
                return (f"entry {e}: refcount {cache.refcount(e)} "
                        f"!= {holds.get(e, 0)} client holds")
        if len(cache._free) + len(cache._entry_prompt) != n_entries:
            return (f"slot conservation: {len(cache._free)} free + "
                    f"{len(cache._entry_prompt)} mapped != "
                    f"{n_entries}")
        return None

    def drain(s):
        cache = s["cache"]
        for ci, e in enumerate(s["clients"]):
            if e is not None:
                cache.release(e)
                s["clients"][ci] = None
        for e in list(cache._entry_prompt):
            cache.invalidate(e)
        if len(cache._free) != n_entries:
            return (f"after release+invalidate of everything "
                    f"{len(cache._free)}/{n_entries} slots free")
        return None

    return Protocol(
        name=f"prefix_cache(entries={n_entries},prompts={n_prompts},"
             f"clients={n_clients})",
        make_init=make_init, actions=actions,
        invariants=[("entry-refcount-conservation", conserve)],
        fingerprint=lambda s: (cache_fingerprint(s["cache"]),
                               tuple(s["clients"])),
        drain=drain)


def radix_protocol(n_blocks: int = 3, n_lanes: int = 2,
                   seqs: Tuple[tuple, ...] = ((7,), (7, 8))
                   ) -> Protocol:
    """Lanes fill exclusive chains for token sequences, insert them
    into the radix tree (tree takes its OWN incref per adopted node),
    admit via the shared-prefix hit path (``acquire`` increfs), retire
    (release shared + decref own — the radix-aware
    ``_free_lane_locked``), and the tree evicts refcount-1 leaves
    under pressure. Invariant: refcounts == lane holds + tree
    adoptions; drain: retire all lanes, evict the whole tree, pool
    all-free. Block size 1, one shared prompt ``(1,)`` (the tree keys
    chains by prompt content). Reference counterpart: none."""
    from ..models.decode_engine import HostBlockPool, RadixBlockTree

    prompt = (1,)

    def make_init():
        pool = HostBlockPool(n_blocks)
        return {"pool": pool, "tree": RadixBlockTree(pool, 1),
                "lanes": [{"blocks": [], "shared": [], "tokens": None,
                           "inserted": False}
                          for _ in range(n_lanes)]}

    def lane_idle(lane):
        return lane["tokens"] is None and not lane["blocks"] \
            and not lane["shared"]

    def retire(lane, tree, pool):
        tree.release(lane["shared"])
        for b in reversed(lane["blocks"]):
            pool.decref(b)
        lane.update(blocks=[], shared=[], tokens=None,
                    inserted=False)

    actions: List[Action] = []
    for li in range(n_lanes):
        for s_i, seq in enumerate(seqs):
            def fill(s, li=li, seq=seq):
                lane = s["lanes"][li]
                lane["blocks"] = [s["pool"].alloc() for _ in seq]
                lane["tokens"] = seq

            actions.append(Action(
                f"fill[{li},{s_i}]",
                lambda s, li=li, seq=seq: (
                    lane_idle(s["lanes"][li])
                    and s["pool"].free_count >= len(seq)),
                fill))

            def hit(s, li=li, seq=seq):
                lane = s["lanes"][li]
                lane["shared"] = s["tree"].acquire(prompt, seq)
                lane["tokens"] = seq

            actions.append(Action(
                f"hit[{li},{s_i}]",
                lambda s, li=li, seq=seq: (
                    lane_idle(s["lanes"][li])
                    and s["tree"].match(prompt, seq) > 0),
                hit))

        def insert(s, li=li):
            lane = s["lanes"][li]
            s["tree"].insert(prompt, lane["tokens"], lane["blocks"])
            lane["inserted"] = True

        actions.append(Action(
            f"insert[{li}]",
            lambda s, li=li: (s["lanes"][li]["tokens"] is not None
                              and bool(s["lanes"][li]["blocks"])
                              and not s["lanes"][li]["inserted"]),
            insert))

        def do_retire(s, li=li):
            retire(s["lanes"][li], s["tree"], s["pool"])

        actions.append(Action(
            f"retire[{li}]",
            lambda s, li=li: s["lanes"][li]["tokens"] is not None,
            do_retire))

        def do_cancel(s, li=li):
            # r20 cancel exit: tree-aware release of the shared
            # prefix + reversed decref of the exclusive tail — the
            # same unwinding _cancel_lane_locked routes through
            # _free_lane_locked (separate closure for the mutation
            # test)
            lane = s["lanes"][li]
            s["tree"].release(lane["shared"])
            for b in reversed(lane["blocks"]):
                s["pool"].decref(b)
            lane.update(blocks=[], shared=[], tokens=None,
                        inserted=False)

        actions.append(Action(
            f"cancel[{li}]",
            lambda s, li=li: s["lanes"][li]["tokens"] is not None,
            do_cancel))

    def evict(s):
        s["tree"].evict(1)

    actions.append(Action(
        "evict",
        lambda s: bool(s["tree"]._roots),
        evict))

    def conserve(s):
        holds: Dict[int, int] = {}
        for lane in s["lanes"]:
            for b in lane["blocks"]:
                holds[b] = holds.get(b, 0) + 1
            for b in lane["shared"]:
                holds[b] = holds.get(b, 0) + 1
        for b in s["tree"].tree_blocks():
            holds[b] = holds.get(b, 0) + 1
        return _conservation(s["pool"], holds)

    def drain(s):
        for lane in s["lanes"]:
            if lane["tokens"] is not None:
                retire(lane, s["tree"], s["pool"])
        while s["tree"].evict(n_blocks):
            pass
        if s["pool"].free_count != n_blocks:
            return (f"after retire+evict of everything "
                    f"{s['pool'].free_count}/{n_blocks} blocks free")
        return None

    return Protocol(
        name=f"radix(n={n_blocks},lanes={n_lanes})",
        make_init=make_init, actions=actions,
        invariants=[("refcount-conservation", conserve)],
        fingerprint=lambda s: (
            pool_fingerprint(s["pool"]), tree_fingerprint(s["tree"]),
            tuple((tuple(l["blocks"]), tuple(l["shared"]),
                   l["tokens"], l["inserted"])
                  for l in s["lanes"])),
        drain=drain)


def session_feasible(n_entries: int, n_prompts: int,
                     allow_close: bool) -> bool:
    """The declarative PTA200 session-capacity predicate this
    module's explorer validates: sessions PIN one prompt entry per
    DISTINCT prompt for their whole lifetime, so admission stays
    live iff sessions can close or the distinct-prompt count fits the
    entry pool. Reference counterpart: none."""
    return allow_close or n_prompts <= n_entries


def session_protocol(n_entries: int, n_prompts: int,
                     allow_close: bool = False) -> Protocol:
    """The session-pinning machine (the CLAUDE.md radix-rules
    deadlock, now mechanized): one session per distinct prompt, each
    needing exactly one turn. ``admit`` acquires the prompt entry
    (``_plan_admissions_locked``), ``harvest`` transfers the entry
    pin from the lane to the session (``_harvest_session_locked`` —
    the ref is RETAINED), ``close`` (only when ``allow_close``)
    releases it (``close_session``). A state where some session still
    wants its turn but nothing is enabled is the admission deadlock;
    with ``n_prompts > n_entries`` and no close the explorer finds it
    with a minimal trace, and ``session_feasible`` must agree on
    every configuration. Reference counterpart: none."""
    from ..models.decode_engine import PromptPrefixCache

    def make_init():
        return {"cache": PromptPrefixCache(n_entries, 1),
                "sessions": [{"st": "want", "entry": None}
                             for _ in range(n_prompts)]}

    actions: List[Action] = []
    for si in range(n_prompts):
        p = (si,)

        def admit(s, si=si, p=p):
            sess = s["sessions"][si]
            sess["entry"] = _cache_acquire(s["cache"], p)
            sess["st"] = "active"

        actions.append(Action(
            f"admit[{si}]",
            lambda s, si=si, p=p: (
                s["sessions"][si]["st"] == "want"
                and _cache_can_acquire(s["cache"], p)),
            admit))

        def harvest(s, si=si):
            s["sessions"][si]["st"] = "pinned"

        actions.append(Action(
            f"harvest[{si}]",
            lambda s, si=si: s["sessions"][si]["st"] == "active",
            harvest))

        def cancel(s, si=si):
            # r20 cancel exit on an ACTIVE (mid-turn) session: the
            # lane's entry ref drops (_cancel_lane_locked) and the
            # turn never harvests — the session itself survives and
            # re-requests, so the state returns to "want". Pinned
            # sessions are untouched (their pin releases only via
            # close_session), so the infeasible-config deadlock
            # witness and session_feasible's verdict are unchanged.
            sess = s["sessions"][si]
            s["cache"].release(sess["entry"])
            sess.update(st="want", entry=None)

        actions.append(Action(
            f"cancel[{si}]",
            lambda s, si=si: s["sessions"][si]["st"] == "active",
            cancel))
        if allow_close:
            def close(s, si=si):
                sess = s["sessions"][si]
                s["cache"].release(sess["entry"])
                sess.update(st="closed", entry=None)

            actions.append(Action(
                f"close[{si}]",
                lambda s, si=si: s["sessions"][si]["st"] == "pinned",
                close))

    def conserve(s):
        holds: Dict[int, int] = {}
        for sess in s["sessions"]:
            if sess["entry"] is not None:
                holds[sess["entry"]] = holds.get(sess["entry"], 0) + 1
        for e in range(n_entries):
            if s["cache"].refcount(e) != holds.get(e, 0):
                return (f"entry {e}: refcount "
                        f"{s['cache'].refcount(e)} != "
                        f"{holds.get(e, 0)} session pins")
        return None

    def accepting(s):
        return all(sess["st"] not in ("want", "active")
                   for sess in s["sessions"])

    return Protocol(
        name=f"session(entries={n_entries},prompts={n_prompts},"
             f"close={allow_close})",
        make_init=make_init, actions=actions,
        invariants=[("pin-refcount-conservation", conserve)],
        fingerprint=lambda s: (
            cache_fingerprint(s["cache"]),
            tuple((sess["st"], sess["entry"])
                  for sess in s["sessions"])),
        accepting=accepting)
