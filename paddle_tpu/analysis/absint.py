"""Abstract interpretation over the Program IR: the divergence &
sharding prover.

Reference counterpart: the reference validates every program in C++
before execution (reference paddle/fluid/framework/op_desc.cc
CheckAttrs/InferShape, operator.cc:975 RunImpl enforcement) but runs
control flow on the HOST, so "is this collective inside a divergent
branch" is not a question its validators can even ask. Here a whole
Block jits into ONE XLA computation and control flow traces into
lax.cond/lax.while_loop — a collective under a predicate that differs
across mesh coordinates deadlocks the chip (the r5 shard_map trap,
re-hit as 1F1B x tp; CLAUDE.md session learnings). The pattern
matcher (checkers.py PTA010/011) catches the lexical shape of that
bug; this module upgrades it to a PROOF: whole-program fixpoint
propagation over three abstract domains, so "this site executes
uniformly" and "this value is replicated across the mesh" become
checkable facts that PR 12's sharded serving lowerings can lean on.

Domains
-------
1. **Divergence contexts** — for every OpSite, the stack of guard
   predicates (while / conditional_block / run_block_if / ifelse
   conditions) the site executes under, each classified by the
   replication fact of its condition value.
2. **Replication lattice** — ``replicated ⊑ varying ⊑ unknown`` per
   value. Seeds: persistables, data vars and constants are
   `replicated` (the single-logical-device build); ops annotated with
   a registered *divergence source* (``divergence_source`` attr —
   lane active masks, pp stage ids, explicit `_vary` casts) or an
   auto-axis sharding annotation (``sharding_axes`` attr) mint
   `varying` values; joins propagate through assign/arith chains and
   through sub-blocks to a fixpoint.
3. **Symbolic shape/dtype** — build-time shape inference clobbers
   declared shapes in place (core/registry.py stashes the original as
   ``_declared_shape``/``_declared_dtype``); `declared_clobbers`
   surfaces declared-vs-producer disagreements (the r10 class) and
   int->float promotions (PTA020 generalized beyond `increment`).
4. **Ownership / index provenance** — symbolic provenance for every
   index reaching a ``@POOL`` read/write (the shared paged-KV pools,
   models/decode_engine.py): a ProvFact tracks which HOST-OWNED index
   sources (block-table feeds, host-deduplicated admission targets,
   refcounted prompt-entry refs — the registered ownership-source
   seed table, each tag carrying a TYPESTATE ``exclusive``/``shared``
   and the named host-allocator assumption that backs it), trace-time
   constants, 0/1 indicators and value BOUNDS a value derives from,
   through the gather/reshape/one-hot-matmul/affine compositions the
   paged lowerings actually use (rules in analysis/ownership_rules.py
   via core.registry.register_index_rule). Checkers PTA190/191/192
   read the recorded PoolAccess facts: provenance+bounds, PROVEN
   lane-exclusive writes (subsuming PTA110's syntactic declaration —
   the ``exclusive_via`` attr survives as the assumption's name), and
   the read-only-while-shared COW contract.

Annotation surface (the seed table)
-----------------------------------
Builders that MINT a predicate that can differ across mesh
coordinates must mark the minting op::

    from paddle_tpu.analysis import absint
    cond = layers.greater_than(live, min_active)
    absint.mark_divergence_source(cond, "lane_active_mask")

New divergence sources (PR 12's sharded lowerings: dp lane shards,
tp/vocab shards) must register a tag first via
``register_divergence_source`` — `mark_divergence_source` refuses
unknown tags so the seed table stays the single source of truth.

Checkers PTA130/131 (checkers.py) read the facts computed here; the
engine itself is pure Python over Program metadata (no jax, no
tracing) and analyzes a whole model program in milliseconds.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.program import Block, Operator, Program
from ..core.registry import EMPTY_VAR
from .dataflow import OpSite, iter_blocks, iter_sub_blocks

__all__ = [
    "REPLICATED", "VARYING", "UNKNOWN", "join",
    "DIVERGENCE_ATTR", "SHARDING_ATTR", "SHARDING_DIMS_ATTR",
    "register_divergence_source", "divergence_sources",
    "mark_divergence_source", "mark_sharded",
    "ValueFact", "GuardFact", "ProgramFacts", "analyze",
    "declared_clobbers",
    "ShardSpec", "TOP_SPEC", "REPLICATED_SPEC", "spec_join",
    "MeshConfig", "set_mesh", "mesh_of",
    "CollectiveEvent", "EventSite",
    "set_device_memory_budget", "device_memory_budget",
    # --- the ownership domain ---
    "POOL_MARK", "OWNERSHIP_ATTR", "OWNERSHIP_BOUND_ATTR",
    "TS_EXCLUSIVE", "TS_SHARED", "TS_GATE",
    "OwnershipSource", "register_pool_index_source",
    "pool_index_sources", "mark_pool_index_source",
    "ProvFact", "prov_join", "PoolAccess",
    # --- the liveness domain ---
    "AcquireContract", "register_acquire_release",
    "register_release_site", "acquire_contracts", "release_sites",
]

# --- the replication lattice ------------------------------------------------
REPLICATED, VARYING, UNKNOWN = "replicated", "varying", "unknown"
_ORDER = {REPLICATED: 0, VARYING: 1, UNKNOWN: 2}


def join(a: str, b: str) -> str:
    """Least upper bound: replicated ⊑ varying ⊑ unknown.

    Reference counterpart: none — standard dataflow lattice join.
    """
    return a if _ORDER[a] >= _ORDER[b] else b


# --- the sharding domain ----------------------------------------------------
# A ShardSpec is the abstract placement of ONE value on the mesh: a
# sparse {tensor dim -> mesh axis} mapping (GSPMD/NamedSharding's
# PartitionSpec, made order-free), with two distinguished points:
# REPLICATED_SPEC (empty mapping — every device holds the full value)
# and TOP_SPEC (placements=None — layout UNKNOWN, the explicit ⊤ an
# op without a registered sharding rule degrades to). The sparse
# form is rank-agnostic, so replicated values never need shape
# bookkeeping and the fixpoint join stays O(1).
@dataclass(frozen=True)
class ShardSpec:
    """Abstract mesh placement of one value (see module docstring).

    Reference counterpart: none — the reference shards at runtime via
    transpilers (transpiler/distribute_transpiler.py); a compile-time
    placement lattice is the GSPMD-era capability this module adds.
    """
    placements: Optional[Tuple[Tuple[int, str], ...]] = ()

    @property
    def is_top(self) -> bool:
        return self.placements is None

    @property
    def is_replicated(self) -> bool:
        return self.placements == ()

    def axis_of(self, dim: int) -> Optional[str]:
        if self.placements is None:
            return None
        for d, a in self.placements:
            if d == dim:
                return a
        return None

    def axes(self):
        return () if self.placements is None else tuple(
            a for _, a in self.placements)

    def describe(self) -> str:
        if self.placements is None:
            return "⊤"
        if not self.placements:
            return "replicated"
        return ",".join(f"dim{d}:{a}" for d, a in self.placements)

    @staticmethod
    def of(placements) -> "ShardSpec":
        """Normalize a {dim: axis} dict / iterable of (dim, axis)
        pairs into a canonical (sorted, deduped) ShardSpec."""
        if placements is None:
            return TOP_SPEC
        if isinstance(placements, dict):
            placements = placements.items()
        return ShardSpec(tuple(sorted(
            (int(d), str(a)) for d, a in placements)))


TOP_SPEC = ShardSpec(None)
REPLICATED_SPEC = ShardSpec(())


def spec_join(a: ShardSpec, b: ShardSpec) -> ShardSpec:
    """Lattice join: equal specs meet at themselves, anything else
    goes to ⊤ — a value written with two different placements has no
    single static layout, and pretending otherwise would let the
    memory planner and the order prover reason from a lie."""
    return a if a == b else TOP_SPEC


@dataclass(frozen=True)
class MeshConfig:
    """Named device mesh a program is built against (SNIPPETS.md
    [1]/[3]'s ``Mesh(devices, ("batch", "model"))`` pattern, as
    static metadata): ordered (axis name, size) pairs. Attached to a
    Program via ``set_mesh`` so the planner can turn propagated
    ShardSpecs into per-DEVICE bytes and the provers can name the
    axes a collective spans.

    Reference counterpart: none — reference device placement was
    per-op attrs (framework/op_desc.cc), not a named mesh.
    """
    axes: Tuple[Tuple[str, int], ...]

    @staticmethod
    def make(**axes) -> "MeshConfig":
        return MeshConfig(tuple((str(k), int(v))
                                for k, v in axes.items()))

    def size(self, name: str, default: int = 1) -> int:
        for n, s in self.axes:
            if n == name:
                return s
        return default

    def n_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    def describe(self) -> str:
        return "x".join(f"{n}={s}" for n, s in self.axes)


def set_mesh(program, mesh: Optional[MeshConfig]) -> None:
    """Attach (or clear) the MeshConfig a program's sharding
    annotations refer to; bumps the version so cached facts refresh."""
    program._mesh_config = mesh
    program._version = getattr(program, "_version", 0) + 1


def mesh_of(program) -> Optional[MeshConfig]:
    return getattr(program, "_mesh_config", None)


def set_device_memory_budget(program, n_bytes: Optional[int]) -> None:
    """Per-program per-DEVICE memory budget in bytes: when set, the
    PTA170 checker turns an over-budget ``device_memory_plan()`` into
    an error diagnostic (the static OOM gate)."""
    program._device_memory_budget = n_bytes
    program._version = getattr(program, "_version", 0) + 1


def device_memory_budget(program) -> Optional[int]:
    return getattr(program, "_device_memory_budget", None)


@dataclass(frozen=True)
class CollectiveEvent:
    """One collective a lowering IMPLIES under the propagated specs
    (not a literal collective op — those are checkers._is_collective):
    kind "psum" (contraction/reduce over a sharded dim), "allgather"
    (gather/consume of a dim-sharded value that must materialize
    fully), "reshard" (GSPMD layout change forced at this site), or
    "conflict" (two consumers/producers demand incompatible specs)."""
    kind: str
    axes: Tuple[str, ...]
    var: Optional[str]
    why: str


@dataclass(frozen=True)
class EventSite:
    """A CollectiveEvent anchored at its op site with the guard stack
    it executes under — the record PTA160/PTA161 read."""
    site: "OpSite"
    guards: tuple
    event: CollectiveEvent


# --- annotation attrs & the divergence-source seed table --------------------
DIVERGENCE_ATTR = "divergence_source"
# optional companion attr: the mesh axes a marked predicate actually
# varies ACROSS (mark_divergence_source(axes=...)). With a MeshConfig
# attached, a mark whose axes are all absent from the mesh is inert —
# the predicate provably cannot differ on a mesh that lacks its axis
# (the tp-sharded serve While: lanes replicated over 'tp', the
# burst-exit predicate varies only across a lane-sharding axis).
# Without a mesh (or without axes) the mark stays unconditionally
# varying — the historical conservative stance.
DIVERGENCE_AXES_ATTR = "divergence_axes"
SHARDING_ATTR = "sharding_axes"
SHARDING_DIMS_ATTR = "sharding_dims"

# tag -> human explanation of WHY values minted under it differ across
# mesh coordinates. This is the seed table the ISSUE/ROADMAP name: a
# new sharded lowering that mints a new predicate family registers its
# tag here (CLAUDE.md conventions) so the prover knows about it.
_DIVERGENCE_SOURCES: Dict[str, str] = {
    "lane_active_mask": (
        "per-lane active/finished masks: once decode lanes shard "
        "across a data-parallel mesh axis, each device sees only its "
        "own lanes' masks — burst-exit predicates derived from them "
        "differ per device"),
    "pp_stage_id": (
        "pipeline-stage coordinate: per-stage predicates (the 1F1B "
        "F/B selector) differ across pp mesh coordinates BY "
        "construction — the r5 deadlock family"),
    "mesh_coord": (
        "a mesh axis index (lax.axis_index analogue): differs across "
        "that axis by definition"),
    "vary": (
        "explicit replicated->varying cast done OUTSIDE divergent "
        "control flow (the r5 `_vary` fix): the value is per-device "
        "from here on, and its grad transpose psum lands at this op, "
        "not inside a branch"),
}


def register_divergence_source(tag: str, description: str) -> None:
    """Add a divergence-source tag to the seed table (idempotent for
    an identical description; refuses silent redefinition).

    Reference counterpart: none — the reference ran control flow on
    the host (reference operators/controlflow/while_op.cc), so a
    cross-device predicate-divergence registry had nothing to gate.
    """
    old = _DIVERGENCE_SOURCES.get(tag)
    if old is not None and old != description:
        raise ValueError(
            f"divergence source {tag!r} already registered with a "
            f"different description; pick a new tag")
    _DIVERGENCE_SOURCES[tag] = description


def divergence_sources() -> Dict[str, str]:
    """The registered seed table, copied. Reference counterpart:
    none (see register_divergence_source)."""
    return dict(_DIVERGENCE_SOURCES)


# --- the ownership domain: pool-index provenance & typestates ---------------
# name mark on SHARED block-pool persistables (models/decode_engine.py
# defines the same literal; analysis stays IR-level and never imports
# models, so the mark is re-declared here as the domain's anchor)
POOL_MARK = "@POOL"

# op attr carrying a mint-site ownership tag (mark_pool_index_source);
# the bound attr carries the host-invariant exclusive upper bound on
# the minted index values (e.g. a block-table entry < n_blocks)
OWNERSHIP_ATTR = "pool_index_source"
OWNERSHIP_BOUND_ATTR = "pool_index_bound"

# typestates of the per-block lifetime lattice
#   free -> exclusive(lane) -> shared(refcount>1) -> freed
# as seen FROM the device program: an index source's typestate says
# what the host allocator guarantees about the blocks/entries it
# addresses at the moment the program runs. TS_GATE is the odd one
# out: not an index source but the active-lane mask a block-table
# write must be gated by (PTA190's gate obligation).
TS_EXCLUSIVE, TS_SHARED, TS_GATE = "exclusive", "shared", "gate"


@dataclass(frozen=True)
class OwnershipSource:
    """One registered pool-index source family: the tag builders mark
    mint sites with, the host typestate it certifies, and the NAMED
    host-allocator assumption the exclusivity proof rests on (the
    property-tested invariant — tests/test_block_pool_model.py).

    Reference counterpart: none — the reference's allocator checks
    are runtime Scope/memory asserts (reference framework/scope.cc);
    a compile-time ownership contract has no analogue there.
    """
    tag: str
    description: str
    typestate: str                  # TS_EXCLUSIVE | TS_SHARED | TS_GATE
    assumption: Optional[str] = None  # named host invariant
    indicator: bool = False         # values provably 0/1 (masks)


# The seed table. The two EXCLUSIVE tags deliberately spell exactly
# like PTA110's ``exclusive_via`` declarations: the prover checks the
# declared via AGREES with the proven provenance, so the old
# declaration survives as the assumption's name (the PTA130/PTA010
# subsumption pattern applied to ownership).
_OWNERSHIP_SOURCES: Dict[str, OwnershipSource] = {}


def register_pool_index_source(tag: str, description: str,
                               typestate: str,
                               assumption: Optional[str] = None,
                               indicator: bool = False) -> None:
    """Add an ownership-source tag to the seed table (idempotent for
    an identical entry; refuses silent redefinition — the
    register_divergence_source contract).

    Reference counterpart: none (see OwnershipSource) — the
    reference's allocator checks are runtime-only."""
    if typestate not in (TS_EXCLUSIVE, TS_SHARED, TS_GATE):
        raise ValueError(
            f"register_pool_index_source: typestate must be one of "
            f"{TS_EXCLUSIVE!r}/{TS_SHARED!r}/{TS_GATE!r}, got "
            f"{typestate!r}")
    entry = OwnershipSource(tag, description, typestate, assumption,
                            indicator)
    old = _OWNERSHIP_SOURCES.get(tag)
    if old is not None and old != entry:
        raise ValueError(
            f"ownership source {tag!r} already registered "
            f"differently; pick a new tag")
    _OWNERSHIP_SOURCES[tag] = entry


def pool_index_sources() -> Dict[str, OwnershipSource]:
    """The registered ownership seed table, copied. Reference
    counterpart: none (see register_pool_index_source)."""
    return dict(_OWNERSHIP_SOURCES)


# the canonical sources every paged lowering uses (models/
# decode_engine.py marks its mint sites with these; the assumption
# names point at the host state machines whose invariants
# tests/test_block_pool_model.py property-tests)
register_pool_index_source(
    "block_table",
    "per-lane block rows the HOST allocator wrote into the fed/"
    "persistable block table: every block in a lane's WRITE-REACHABLE "
    "suffix (table positions >= the lane's resume step page) is "
    "exclusive to it (HostBlockPool refcount==1 between alloc and "
    "free/decref), while radix-shared blocks (refcount>1) appear "
    "only in the read-only prefix BELOW the resume step — so the "
    "step body's act-gated current-position write always lands in "
    "an exclusive block, and distinct lanes' writable rows are "
    "disjoint",
    TS_EXCLUSIVE, assumption="HostBlockPool.alloc-disjoint")
register_pool_index_source(
    "host_indices",
    "host-deduplicated admission targets (prompt-entry slots fed per "
    "admission): the scheduler feeds pairwise-distinct FRESH entries "
    "(PromptPrefixCache.acquire_fresh, refcount==1 at write time) "
    "with padded rows aimed at the dustbin entry",
    TS_EXCLUSIVE, assumption="PromptPrefixCache.fresh-exclusive")
register_pool_index_source(
    "prompt_entry_ref",
    "per-lane prompt-entry refs: entries are REFCOUNTED across lanes "
    "with identical prompts (refcount may exceed 1), so these "
    "indices certify reads only — a write through them is the "
    "write-while-shared COW violation PTA192 rejects",
    TS_SHARED)
register_pool_index_source(
    "lane_active",
    "per-lane active mask (0/1 by the slot-state contract): the gate "
    "a block-table pool write must carry so idle/dustbin/paused "
    "lanes write nothing",
    TS_GATE, indicator=True)
register_pool_index_source(
    "cow_src",
    "COW copy sources: blocks of a radix-SHARED chain "
    "(HostBlockPool refcount>=1, typically >1) the host feeds to "
    "the bundle's cow program — read-legal (the gather side of the "
    "copy), write-ILLEGAL: an index with this tag reaching a pool "
    "write is exactly the write-while-shared violation PTA192 "
    "rejects",
    TS_SHARED)
register_pool_index_source(
    "cow_dst",
    "COW copy destinations: blocks freshly popped from "
    "HostBlockPool.alloc (refcount==1, exclusive) for this copy "
    "dispatch, pairwise-distinct and disjoint from every live "
    "chain; padded rows aim at -1 (the trash row) under gate 0 — "
    "the exclusive write window a lane diverges into when it "
    "branches off a shared prefix",
    TS_EXCLUSIVE, assumption="HostBlockPool.cow-fresh-exclusive")
register_pool_index_source(
    "chunk_cursor",
    "chunked-prefill position cursor (the `chunk_pos` feed): the "
    "host walks it 0, C, 2C, ... < seq_len across ONE prompt whose "
    "entry stays fresh-exclusive (refcount==1, unpublished) for the "
    "whole multi-phase prefill — it selects POSITIONS inside that "
    "exclusive entry's staging/cross rows, never a pool row, so "
    "every write it parameterizes stays inside the host_indices "
    "exclusivity window",
    TS_EXCLUSIVE, assumption="PromptPrefixCache.fresh-exclusive")


# --- the liveness domain: acquire/release obligation contracts ---------------
# Where an OwnershipSource certifies what a minted index MEANS, an
# AcquireContract declares the OBLIGATION minting through that tag
# creates: the host call that takes the hold, the host call that
# discharges it, and the exhaustive set of protocol exit paths the
# discharge must be proven on. The contract store lives in
# core/registry.py beside the sharding/index rule stores; this module
# owns validation (tags must exist in the ownership seed table and
# must not be gates — a 0/1 mask is not a resource) so a typo'd tag
# fails at import, not as a silently-empty ledger.
@dataclass(frozen=True)
class AcquireContract:
    """One acquire/release obligation family for a resource tag.

    ``acquire``/``release`` name the host calls ("Class.method") that
    mint and discharge the hold; ``exits`` is the exhaustive tuple of
    protocol exit paths on which PTA201 requires a registered release
    site; ``resource`` names the allocator machine the hold draws
    from (the protomodel/PTA200 capacity pool it counts against).

    Reference counterpart: none — the reference discharges at runtime
    via scoped GC (reference framework/executor.cc Scope teardown); a
    static per-exit-path obligation has no analogue there.
    """
    tag: str
    acquire: str
    release: str
    exits: Tuple[str, ...]
    resource: str


def register_acquire_release(tag: str, acquire: str, release: str,
                             exits: Iterable[str],
                             resource: str) -> AcquireContract:
    """Register the liveness contract for ownership tag ``tag``
    (idempotent-identical, raise-on-redefinition — the standing
    registry contract). The tag must already be a registered
    NON-GATE ownership source: contracts attach obligations to real
    resource holds, and registering first forces the mint-site mark
    to exist before anyone claims its release story.

    Reference counterpart: none (see AcquireContract)."""
    from ..core import registry as _registry

    src = _OWNERSHIP_SOURCES.get(tag)
    if src is None:
        raise ValueError(
            f"register_acquire_release: {tag!r} is not a registered "
            f"ownership source (register_pool_index_source first)")
    if src.typestate == TS_GATE:
        raise ValueError(
            f"register_acquire_release: {tag!r} is a gate (0/1 "
            f"mask), not a resource hold — gates carry no obligation")
    exits = tuple(exits)
    if not exits:
        raise ValueError(
            f"register_acquire_release: {tag!r} declares no exit "
            f"paths — an obligation with no discharge path is a "
            f"declared leak, suppress it at the checker instead")
    contract = AcquireContract(tag, acquire, release, exits, resource)
    _registry.register_acquire_contract(tag, contract)
    return contract


def register_release_site(tag: str, exit_path: str,
                          site: str) -> None:
    """Record that ``site`` discharges ``tag``'s obligation on
    ``exit_path``. The contract must exist and must declare the exit
    — a release on an undeclared path means the contract's exit set
    is stale, which is exactly the drift PTA201 exists to catch, so
    it raises here rather than widening silently.

    Reference counterpart: none (see AcquireContract)."""
    from ..core import registry as _registry

    contract = _registry.get_acquire_contract(tag)
    if contract is None:
        raise ValueError(
            f"register_release_site: no acquire contract for "
            f"{tag!r} (register_acquire_release first)")
    if exit_path not in contract.exits:
        raise ValueError(
            f"register_release_site: {tag!r} does not declare exit "
            f"path {exit_path!r} (declared: {contract.exits}); "
            f"extend the contract, don't widen it from a call site")
    _registry.register_release_site(tag, exit_path, site)


def acquire_contracts() -> Dict[str, AcquireContract]:
    """The registered contract table, copied. Reference counterpart:
    none (see AcquireContract)."""
    from ..core import registry as _registry

    return _registry.acquire_contracts()


def release_sites() -> Dict[Tuple[str, str], List[str]]:
    """The registered release-site table, copied. Reference
    counterpart: none (see AcquireContract)."""
    from ..core import registry as _registry

    return _registry.release_sites()


# The canonical contracts for the serving-era tags above. Exit-path
# vocabulary (shared with inference/serving.py's site registrations):
#   retire        normal lane retirement (_free_lane_locked)
#   preempt       recompute-preemption of a live lane
#   abort         abandoned chunked-prefill job teardown
#   invalidate    admission backout / entry invalidation
#   session_close close_session releasing a pinned entry
#   server_close  close() draining lanes, jobs, and handoff refs
#   handoff       disagg prefill->decode ownership transfer
#   cancel        client cancellation / deadline expiry (the r20
#                 front door): a queued, chunking, or LIVE request is
#                 torn down mid-hold at the next burst boundary.
#                 Deadline expiry RIDES this exit (a deadline miss is
#                 a server-initiated cancel — same release path, a
#                 different recorded reason), so one exit covers both
#                 and a tag with no cancel site leaks once per
#                 abandoned request until admission wedges.
register_acquire_release(
    "block_table", acquire="HostBlockPool.alloc",
    release="HostBlockPool.decref",
    exits=("retire", "preempt", "cancel", "server_close"),
    resource="HostBlockPool")
register_acquire_release(
    "host_indices", acquire="PromptPrefixCache.acquire_fresh",
    release="PromptPrefixCache.release",
    exits=("retire", "abort", "invalidate", "cancel",
           "server_close"),
    resource="PromptPrefixCache")
register_acquire_release(
    "prompt_entry_ref", acquire="PromptPrefixCache.acquire_hit",
    release="PromptPrefixCache.release",
    exits=("retire", "session_close", "cancel", "server_close"),
    resource="PromptPrefixCache")
register_acquire_release(
    "cow_src", acquire="RadixBlockTree.acquire",
    release="RadixBlockTree.release",
    exits=("retire", "preempt", "evict", "cancel", "server_close"),
    resource="HostBlockPool")
register_acquire_release(
    "cow_dst", acquire="HostBlockPool.alloc",
    release="HostBlockPool.decref",
    exits=("retire", "preempt", "cancel", "server_close"),
    resource="HostBlockPool")
register_acquire_release(
    "chunk_cursor", acquire="PromptPrefixCache.acquire_fresh",
    release="PromptPrefixCache.release",
    exits=("handoff", "abort", "cancel", "server_close"),
    resource="PromptPrefixCache")


@dataclass(frozen=True)
class ProvFact:
    """Symbolic provenance of one value, as an index candidate.

    ``tags``: ownership-source tags the value derives from (sorted).
    ``const``: every contribution is a trace-time constant.
    ``indicator``: values provably in {0, 1} (comparison mints, the
    active mask, products of indicators).
    ``onehot``: an indicator with AT MOST ONE nonzero in each
    leading-index row's trailing block — ``oh_tail`` records HOW MANY
    trailing axes that block spans (1 at the `equal`-against-a-
    distinct-`range` mint; a last-axis-splitting reshape widens it).
    The extent is load-bearing: a reshape that folds leading axes
    into the block, a concat along it, or a reduce outside it breaks
    the property, and the rules must drop the flag there rather than
    certify a lying bound downstream.
    ``selection``: product of a bounded value with a one-hot — a
    reduce over the one-hot's trailing block picks at most one
    entry, so tags/bound survive the sum (``oh_tail`` carries the
    selector's block extent through to the reduce).
    ``distinct``: constant with pairwise-distinct entries (range /
    arange mints) — the operand that makes an `equal` one-hot.
    ``bound``: exclusive upper bound on the (integer) values when
    provable; None = unbounded/unknown.
    ``nonneg``: values provably >= 0. Mints of negative constants
    produce NO fact at all; this flag exists because subtraction can
    turn a non-negative fact negative, and the sub/mul/scale bound
    arithmetic is only sound over non-negative operands — a rule
    must consult it before reusing a bound (ownership_rules.py).
    ``chain``: mint-site + transform anchors (capped) — the
    provenance chain PTA190 prints on a failed proof.

    Reference counterpart: none — the reference's allocator safety
    was runtime Scope/memory asserts (reference framework/scope.cc);
    a static provenance fact has nothing to mirror there.
    """
    tags: Tuple[str, ...] = ()
    const: bool = False
    indicator: bool = False
    onehot: bool = False
    selection: bool = False
    distinct: bool = False
    bound: Optional[int] = None
    nonneg: bool = True
    oh_tail: int = 0
    chain: Tuple[str, ...] = ()

    def with_step(self, anchor: str, **changes) -> "ProvFact":
        chain = self.chain if len(self.chain) >= 8 \
            else self.chain + (anchor,)
        return ProvFact(**{**self.__dict__, **changes,
                           "chain": chain})

    def typestates(self) -> Tuple[str, ...]:
        return tuple(sorted({
            _OWNERSHIP_SOURCES[t].typestate for t in self.tags
            if t in _OWNERSHIP_SOURCES}))

    def describe(self) -> str:
        bits = []
        if self.tags:
            bits.append("tags=" + ",".join(self.tags))
        if self.const:
            bits.append("const")
        if self.onehot:
            bits.append("one-hot")
        elif self.indicator:
            bits.append("indicator")
        if self.bound is not None:
            bits.append(f"bound<{self.bound}")
        return "{" + " ".join(bits or ["unknown"]) + "}"


def prov_join(a: ProvFact, b: ProvFact) -> ProvFact:
    """Join of two writers of one name: union the tags, keep a
    property only when BOTH sides have it, weaken the bound to the
    larger one (None wins — unbounded).

    Reference counterpart: none — standard dataflow lattice join
    (see ProvFact)."""
    bound = None
    if a.bound is not None and b.bound is not None:
        bound = max(a.bound, b.bound)
    lead = a if a.chain else b
    both_oh = a.onehot and b.onehot
    both_sel = a.selection and b.selection
    # the larger trailing block is the STRONGER claim; the join
    # keeps the weaker (smaller) one
    tail = min(a.oh_tail, b.oh_tail) if (both_oh or both_sel) else 0
    return ProvFact(tuple(sorted(set(a.tags) | set(b.tags))),
                    a.const and b.const,
                    a.indicator and b.indicator,
                    both_oh, both_sel,
                    a.distinct and b.distinct,
                    bound, a.nonneg and b.nonneg, tail, lead.chain)


@dataclass(frozen=True)
class PoolAccess:
    """One read/write of a ``@POOL`` persistable, with the resolved
    index/gate provenance — the record PTA190/191/192 judge.
    ``axis_size`` is the extent of the indexed leading axis (the
    flattened cell count for a write, the gathered view's first dim
    for a read) when statically known — the in-bounds half of
    PTA190's proof compares the index fact's bound against it.

    Reference counterpart: none — the closest thing in the
    reference is the runtime bounds assert inside each kernel
    (reference operators/gather_op.h); a build-time access record
    has no analogue."""
    site: "OpSite"
    guards: tuple
    kind: str                       # "read" | "write"
    pool: str                       # the @POOL var name
    index_var: Optional[str]
    index_fact: Optional[ProvFact]
    gate_var: Optional[str] = None
    gate_fact: Optional[ProvFact] = None
    axis_size: Optional[int] = None


def _producer_op(var) -> Optional[Operator]:
    """Most recent op writing `var` (searched from the var's program,
    current block first — the helper is called right after the layer
    call appends the producer)."""
    name = getattr(var, "name", var)
    blk = getattr(var, "block", None)
    program = blk.program if blk is not None else None
    if program is None:
        return None
    blocks = [program.current_block()] + list(program.blocks)
    seen = set()
    for b in blocks:
        if id(b) in seen:
            continue
        seen.add(id(b))
        for op in reversed(b.ops):
            if name in op.output_arg_names:
                return op
    return None


def mark_divergence_source(var, tag: str, axes=None) -> None:
    """Build-time annotation: mark the producer op of `var` as minting
    a mesh-varying value (tag must be in the registered seed table).
    The abstract interpreter seeds the replication lattice from these
    marks; collectives/grads guarded by values derived from them get
    PROVEN-divergent diagnostics (PTA130/131) instead of pattern
    guesses.

    ``axes`` (optional) names the mesh axes the predicate varies
    ACROSS. When given AND the program carries a MeshConfig
    (``set_mesh``) that has none of those axes at size > 1, the mark
    is inert — the predicate provably cannot differ on a mesh lacking
    its axis, so the guard classifies from its actual inputs instead
    (the tp-sharded serve While's burst-exit predicate: lanes are
    replicated over 'tp'; the predicate varies only across a
    lane-sharding axis, which the tp mesh does not have). Without
    axes, or without a mesh, the mark stays unconditionally varying —
    the conservative historical stance.

    Reference counterpart: none (see register_divergence_source);
    compile-time capability of the whole-block-jit executor.
    """
    if tag not in _DIVERGENCE_SOURCES:
        raise ValueError(
            f"unknown divergence source {tag!r}; register it first "
            f"(absint.register_divergence_source) — known: "
            f"{sorted(_DIVERGENCE_SOURCES)}")
    op = _producer_op(var)
    if op is None:
        raise ValueError(
            f"mark_divergence_source: no producer op found for "
            f"{getattr(var, 'name', var)!r}")
    op.attrs[DIVERGENCE_ATTR] = tag
    if axes is not None:
        op.attrs[DIVERGENCE_AXES_ATTR] = tuple(
            str(a) for a in (axes if isinstance(axes, (list, tuple))
                             else (axes,)))
    blk = getattr(var, "block", None)
    if blk is not None and blk.program is not None:
        blk.program._version += 1  # invalidate cached fingerprints/facts


def _parse_sharding(var, axes):
    """(axis_names, dim_placements|None) from the two accepted forms:

    * ``{dim: axis}`` dict (or (dim, axis) pairs) — the full per-dim
      placement the sharding DOMAIN propagates (negative dims resolve
      against the var's rank when known);
    * a bare axis name or sequence of names — the legacy
      which-axes-touch-this-value form (dims unknown: the replication
      lattice still marks the value varying, the spec domain pins ⊤).
    """
    if isinstance(axes, dict) or (
            isinstance(axes, (list, tuple)) and axes and all(
                isinstance(e, (list, tuple)) and len(e) == 2
                for e in axes)):
        items = axes.items() if isinstance(axes, dict) else axes
        rank = None
        shape = getattr(var, "shape", None)
        if shape is not None:
            rank = len(shape)
        placements = []
        for d, a in items:
            d = int(d)
            if d < 0:
                if rank is None:
                    raise ValueError(
                        f"mark_sharded: negative dim {d} needs a var "
                        f"with a known shape")
                d += rank
            if rank is not None and not (0 <= d < rank):
                raise ValueError(
                    f"mark_sharded: dim {d} out of range for shape "
                    f"{tuple(shape)}")
            placements.append((d, str(a)))
        spec = ShardSpec.of(placements)
        return tuple(a for _, a in spec.placements), spec.placements
    names = tuple(axes) if isinstance(axes, (list, tuple)) else (axes,)
    return tuple(str(a) for a in names), None


def mark_sharded(var, axes) -> None:
    """Mark `var` as carrying an auto-axis sharding annotation (the
    with_sharding_constraint analogue PR 12+'s lowerings emit): GSPMD
    may insert collectives wherever the value is consumed, so the
    prover treats it as varying and PTA131 rejects reads of it inside
    divergent contexts. The dict form ``{dim: axis}`` additionally
    pins the value's ShardSpec for the sharding domain (PTA160/161
    propagation, the PTA170 per-device planner).

    The annotation rides BOTH the producer op (when one exists) and
    the Variable itself: data/feed vars and parameters have no
    producer in an inference/step program, yet sharded INPUTS are
    precisely the sharded-serving entry point — the var-level seed is
    what lets a builder annotate them at all.

    Reference counterpart: the reference annotated placement per op
    (reference framework/op_desc.cc device attrs); GSPMD auto-axis
    annotations whose collectives MOVE have no analogue there.
    """
    names, placements = _parse_sharding(var, axes)
    op = _producer_op(var)
    if op is not None and any(True for _ in iter_sub_blocks(op)):
        # a CONTAINER op (while/cond) lists every carried name as an
        # output — pinning the op would smear this var's placement
        # onto every co-carried output (annotating a while-carried KV
        # buffer must not shard the loop counter). The body's real
        # writer ops are walked anyway; the var-level seed below is
        # what holds the annotation.
        op = None
    if op is None and getattr(var, "block", None) is None:
        raise ValueError(
            f"mark_sharded: {getattr(var, 'name', var)!r} has neither "
            f"a producer op nor a Variable to seed — pass the "
            f"Variable object (layers.data / block.create_var result)")
    if op is not None:
        op.attrs[SHARDING_ATTR] = names
        if placements is not None:
            op.attrs[SHARDING_DIMS_ATTR] = placements
    if getattr(var, "block", None) is not None:
        # var-level seed: producer-less vars (feeds, parameters) AND
        # read-before-write state see the annotation from iteration 1
        var._sharding_axes = names
        var._sharding_dims = placements
    blk = getattr(var, "block", None)
    if blk is not None and blk.program is not None:
        blk.program._version += 1


def mark_pool_index_source(var, tag: str,
                           bound: Optional[int] = None) -> None:
    """Build-time annotation: mark `var` as a HOST-OWNED pool-index
    source of family `tag` (must be in the registered ownership seed
    table). The ownership domain seeds its provenance facts from
    these marks; an index reaching a ``@POOL`` access whose
    provenance does not chain to a marked source (or a trace-time
    constant) is a PTA190 error with the chain printed.

    `bound` is the host invariant's exclusive upper bound on the
    minted values (a block-table entry < n_blocks, a prompt ref <=
    the dustbin entry): it feeds the in-bounds half of PTA190 through
    the affine composition rules.

    Like ``mark_sharded``, the annotation rides the producer op when
    one exists AND the Variable itself — fed tables and persistable
    scope state have no producer in a step program, yet host-written
    tables are precisely the ownership entry point.

    Reference counterpart: none (see OwnershipSource) — the
    reference's allocator checks are runtime-only.
    """
    if tag not in _OWNERSHIP_SOURCES:
        raise ValueError(
            f"unknown ownership source {tag!r}; register it first "
            f"(absint.register_pool_index_source) — known: "
            f"{sorted(_OWNERSHIP_SOURCES)}")
    op = _producer_op(var)
    if op is None and getattr(var, "block", None) is None:
        raise ValueError(
            f"mark_pool_index_source: {getattr(var, 'name', var)!r} "
            f"has neither a producer op nor a Variable to seed — "
            f"pass the Variable object")
    if op is not None:
        op.attrs[OWNERSHIP_ATTR] = tag
        if bound is not None:
            op.attrs[OWNERSHIP_BOUND_ATTR] = int(bound)
    if getattr(var, "block", None) is not None:
        var._ownership_tag = tag
        var._ownership_bound = int(bound) if bound is not None \
            else None
    blk = getattr(var, "block", None)
    if blk is not None and blk.program is not None:
        blk.program._version += 1


# --- facts ------------------------------------------------------------------
@dataclass(frozen=True)
class ValueFact:
    """Abstract value of one var name."""
    repl: str = REPLICATED          # REPLICATED | VARYING | UNKNOWN
    source: Optional[str] = None    # divergence tag when VARYING
    minted_at: Optional[str] = None  # anchor of the minting op
    sharded: Optional[tuple] = None  # sharding axes annotation, if any
    # True when ANY varying ancestry came from a MANUAL divergence
    # source (the registered seed table: pp_stage_id, mesh_coord,
    # lane_active_mask, vary) as opposed to GSPMD auto-axis sharding
    # annotations. STICKY across joins: a predicate mixing sharded
    # values with a stage id is manually divergent no matter which
    # operand's source string survives the join — the GSPMD-uniform
    # guard reclassification must never fire for it.
    manual: bool = False

    def joined(self, other: "ValueFact") -> "ValueFact":
        repl = join(self.repl, other.repl)
        # keep the explanation of whichever side made us varying;
        # between two varying sides, prefer the MANUAL one — its tag
        # names the real divergence source in diagnostics
        lead = self if _ORDER[self.repl] >= _ORDER[other.repl] else other
        if self.repl == VARYING and other.repl == VARYING \
                and lead.source and str(lead.source).startswith(
                    "sharding:"):
            alt = other if lead is self else self
            if alt.manual:
                lead = alt
        return ValueFact(repl, lead.source, lead.minted_at,
                         self.sharded or other.sharded,
                         self.manual or other.manual)


@dataclass(frozen=True)
class GuardFact:
    """One divergent-control-flow predicate a site executes under."""
    container_type: str             # while / conditional_block / ...
    container_anchor: str           # OpSite.anchor() of the container
    cond_var: Optional[str]         # predicate var name
    fact: str                       # replication class of the predicate
    source: Optional[str] = None    # divergence tag when proven varying
    minted_at: Optional[str] = None

    def describe(self) -> str:
        what = f"{self.container_type} guard {self.cond_var!r}"
        if self.fact == VARYING:
            src = _DIVERGENCE_SOURCES.get(self.source or "", "")
            out = (f"{what}: PROVEN divergent across mesh coordinates "
                   f"(source {self.source!r}")
            if self.minted_at:
                out += f", minted at {self.minted_at}"
            out += ")"
            if src:
                out += f" — {src}"
            return out
        if self.fact == UNKNOWN:
            return (f"{what}: divergence UNPROVABLE (predicate derives "
                    f"from values outside the replication facts)")
        if self.source and str(self.source).startswith("sharding:"):
            return (f"{what}: value-uniform — its only varying "
                    f"ancestry is GSPMD auto-axis sharding "
                    f"({self.source}); the partitioner computes "
                    f"predicates consistently on every device, so "
                    f"control flow stays uniform (no manual "
                    f"divergence source in its chain)")
        return (f"{what}: value-uniform under current replication "
                f"facts (facts assume unsharded feeds)")


@dataclass
class ProgramFacts:
    """Result of one fixpoint run over a Program."""
    program: Program
    values: Dict[str, ValueFact] = field(default_factory=dict)
    # id(op) -> guard stack (outermost first); only guarded ops appear
    _guards: Dict[int, Tuple[GuardFact, ...]] = field(
        default_factory=dict)
    # every site, recorded in walk order (guarded or not)
    sites: List[OpSite] = field(default_factory=list)
    iterations: int = 0
    converged: bool = True
    # --- the sharding domain ---
    specs: Dict[str, ShardSpec] = field(default_factory=dict)
    pinned: Dict[str, ShardSpec] = field(default_factory=dict)
    # sharding-implied collectives/reshards, in walk order
    collective_events: List[EventSite] = field(default_factory=list)
    mesh: Optional[MeshConfig] = None
    # --- the ownership domain ---
    prov: Dict[str, ProvFact] = field(default_factory=dict)
    pool_accesses: List[PoolAccess] = field(default_factory=list)

    def value(self, name: str) -> ValueFact:
        return self.values.get(name, ValueFact(REPLICATED))

    def spec(self, name: str) -> ShardSpec:
        got = self.pinned.get(name)
        if got is not None:
            return got
        return self.specs.get(name, REPLICATED_SPEC)

    def nontrivial_specs(self) -> Dict[str, str]:
        """{var: spec description} for every var whose propagated (or
        pinned) spec is not plain-replicated — the snapshot the CI
        baseline's ``sharding_facts`` section drift-gates."""
        out = {}
        for name in set(self.specs) | set(self.pinned):
            s = self.spec(name)
            if not s.is_replicated:
                out[name] = s.describe()
        return out

    def stable_sharding_facts(self) -> Dict[str, str]:
        """``nontrivial_specs`` restricted to STABLY-named vars —
        pinned annotations plus persistable/data vars: auto-generated
        temp names (tmp_N) shift with process-global build order, so
        only the stable surface feeds the CI baseline's
        ``sharding_facts`` drift gate (analysis/baseline.py)."""
        stable = {}
        named = set(self.pinned)
        for blk, _ in iter_blocks(self.program):
            for name, var in blk.vars.items():
                if var.persistable or var.is_data:
                    named.add(name)
        for name, desc in self.nontrivial_specs().items():
            if name in named:
                stable[name] = desc
        if self.mesh is not None and stable:
            stable["@mesh"] = self.mesh.describe()
        return stable

    def device_memory_plan(self, batch: int = 1):
        """Static per-device memory plan for the program under the
        propagated specs (analysis/memplan.py): bytes per persistable
        / feed / temp, totals and per-device totals. `batch`
        substitutes dynamic (-1) dims."""
        from . import memplan

        return memplan.build_plan(self, batch=batch)

    def guards(self, op: Operator) -> Tuple[GuardFact, ...]:
        return self._guards.get(id(op), ())

    def guarded_sites(self) -> Iterable[Tuple[OpSite,
                                              Tuple[GuardFact, ...]]]:
        for site in self.sites:
            g = self._guards.get(id(site.op))
            if g:
                yield site, g

    def divergent(self, guards: Tuple[GuardFact, ...]) -> bool:
        return any(g.fact == VARYING for g in guards)

    def unproven(self, guards: Tuple[GuardFact, ...]) -> bool:
        return any(g.fact in (VARYING, UNKNOWN) for g in guards)

    # --- the ownership surface -----------------------------------------
    def prov_of(self, name: str) -> Optional[ProvFact]:
        return self.prov.get(name)

    def ownership_ledger(self) -> dict:
        """The assumptions/obligations ledger of this program's pool
        accesses: which NAMED host-allocator invariants the proofs
        rest on (with site counts), how many accesses the domain
        proved, and which remain unproven — the CLI's --json
        ownership surface and the CI baseline's raw material."""
        assumptions: Dict[str, int] = {}
        obligations: Dict[str, int] = {}
        proven_w = proven_r = unproven = 0
        for acc in self.pool_accesses:
            fact = acc.index_fact
            tags = fact.tags if fact is not None else ()
            ok = fact is not None and (
                fact.const or (tags and all(
                    t in _OWNERSHIP_SOURCES for t in tags)))
            if not ok:
                unproven += 1
                continue
            if acc.kind == "write":
                proven_w += 1
            else:
                proven_r += 1
            for t in tags:
                src = _OWNERSHIP_SOURCES[t]
                if src.assumption:
                    assumptions[src.assumption] = \
                        assumptions.get(src.assumption, 0) + 1
            if acc.kind == "write" and acc.gate_fact is not None \
                    and any(_OWNERSHIP_SOURCES.get(t) is not None
                            and _OWNERSHIP_SOURCES[t].typestate
                            == TS_GATE
                            for t in acc.gate_fact.tags):
                obligations["gate=lane_active"] = \
                    obligations.get("gate=lane_active", 0) + 1
        return {"assumptions": assumptions,
                "obligations": obligations,
                "proven_writes": proven_w, "proven_reads": proven_r,
                "unproven": unproven}

    def stable_ownership_facts(self) -> Dict[str, str]:
        """Per-pool access summary over STABLE names (the pools are
        persistables), for the CI baseline's drift-gated
        ``ownership_facts`` section: a provenance-rule or annotation
        change that silently re-derives a pool access shows up as a
        value diff, exactly like ``sharding_facts``."""
        per_pool: Dict[str, Dict[str, set]] = {}
        for acc in self.pool_accesses:
            slot = per_pool.setdefault(acc.pool,
                                       {"read": set(), "write": set()})
            fact = acc.index_fact
            if fact is None:
                desc = "unknown"
            elif fact.tags:
                parts = []
                for t in fact.tags:
                    src = _OWNERSHIP_SOURCES.get(t)
                    if src is not None and src.assumption:
                        parts.append(f"{t}⊢{src.assumption}")
                    else:
                        parts.append(t)
                desc = ",".join(parts)
            elif fact.const:
                desc = "const"
            else:
                desc = "unknown"
            if acc.kind == "write" and acc.gate_fact is not None \
                    and acc.gate_fact.tags:
                desc += f" gate={','.join(acc.gate_fact.tags)}"
            slot[acc.kind].add(desc)
        out = {}
        for pool, kinds in per_pool.items():
            bits = []
            for kind in ("write", "read"):
                if kinds[kind]:
                    bits.append(
                        f"{kind}s[{';'.join(sorted(kinds[kind]))}]")
            out[pool] = " ".join(bits)
        ledger = self.ownership_ledger()
        if out and ledger["assumptions"]:
            out["@assumptions"] = ",".join(
                sorted(ledger["assumptions"]))
        return out


# container op type -> input slot holding the branch predicate
# (mirrors checkers.DIVERGENT_CONTAINERS; the kernels are in
# ops/control_flow_ops.py and ops/lod_ops.py)
_COND_SLOTS = {
    "while": "Condition",
    "run_block_if": "Condition",
    "conditional_block": "Condition",
    "ifelse": "Cond",
}

_MAX_ITERS = 16


class _Interp:
    """One fixpoint run. Values live in ONE name->fact map: var names
    are program-unique in practice (sub-block kernels resolve parent
    names by identity), and the join makes any accidental collision
    err toward varying/unknown — conservative, never silently
    uniform. The sharding domain runs in the SAME walk: per-op
    propagation rules (core/registry.py register_sharding_rule) carry
    ShardSpecs forward, annotation pins hold them fixed, and the
    collectives a lowering implies are recorded per site with the
    guard stack they would execute under."""

    def __init__(self, program: Program):
        self.program = program
        self.values: Dict[str, ValueFact] = {}
        self.guards: Dict[int, Tuple[GuardFact, ...]] = {}
        self.sites: List[OpSite] = []
        self.changed = False
        self.mesh = mesh_of(program)
        self.specs: Dict[str, ShardSpec] = {}
        self.events: List[EventSite] = []
        self._top_warned: set = set()
        # --- the ownership domain ---
        self.prov: Dict[str, ProvFact] = {}
        self.pool_accesses: List[PoolAccess] = []
        # pool VIEWS: names that alias a @POOL var through pure
        # view ops (reshape/transpose/...) — a gather off one is a
        # pool READ whose index PTA190 must judge
        self.pool_views: Dict[str, str] = {}
        # var-level ownership pins (mark_pool_index_source on fed/
        # persistable tables): the annotation HOLDS — in-program
        # writers (the active mask's RMW update) never weaken it
        self.prov_pins: Dict[str, ProvFact] = {}
        # spec pins: var-level annotations (mark_sharded on feeds /
        # parameters / state) plus op-level dim annotations — the
        # with_sharding_constraint analogue: the annotated name HOLDS
        # its spec; a producer that disagrees is an implicit reshard
        # fact, not a join to ⊤
        self.pins: Dict[str, ShardSpec] = {}
        for blk, _ in iter_blocks(program):
            for name, var in blk.vars.items():
                tag = getattr(var, "_ownership_tag", None)
                if tag is not None and tag in _OWNERSHIP_SOURCES:
                    src = _OWNERSHIP_SOURCES[tag]
                    self.prov_pins[name] = ProvFact(
                        tags=(tag,), indicator=src.indicator,
                        bound=getattr(var, "_ownership_bound", None),
                        chain=(f"{tag} mark on {name!r}",))
                dims = getattr(var, "_sharding_dims", None)
                axes = getattr(var, "_sharding_axes", None)
                if dims is not None:
                    self.pins[name] = ShardSpec.of(dims)
                elif axes is not None:
                    self.pins.setdefault(name, TOP_SPEC)
                if axes is not None:
                    # var-level annotations (producer-less feeds/
                    # params/state) mint VARYING from iteration 1 —
                    # sharded values invite GSPMD collectives at
                    # their consumers (PTA131's premise)
                    self.values[name] = ValueFact(
                        VARYING, f"sharding:{tuple(axes)}", None,
                        sharded=tuple(axes))
            for op in blk.ops:
                dims = op.attrs.get(SHARDING_DIMS_ATTR)
                if dims is not None:
                    for n in op.output_arg_names:
                        if n != EMPTY_VAR:
                            self.pins.setdefault(n, ShardSpec.of(dims))

    def run(self) -> ProgramFacts:
        # rule families register at first use (import side effect),
        # mirroring how kernels register at ops/ import
        from . import ownership_rules  # noqa: F401
        from . import sharding_rules  # noqa: F401

        iters = 0
        converged = False
        for iters in range(1, _MAX_ITERS + 1):
            self.changed = False
            self.guards.clear()
            self.sites = []
            self.events = []
            self.pool_accesses = []
            self.pool_views = {}
            for blk, container in self._top_blocks():
                self._walk(blk, container, ())
            if not self.changed:
                converged = True
                break
        prov = dict(self.prov)
        prov.update(self.prov_pins)   # pins win (the annotation HOLDS)
        facts = ProgramFacts(self.program, dict(self.values),
                             dict(self.guards), list(self.sites),
                             iterations=iters, converged=converged,
                             specs=dict(self.specs),
                             pinned=dict(self.pins),
                             collective_events=list(self.events),
                             mesh=self.mesh,
                             prov=prov,
                             pool_accesses=list(self.pool_accesses))
        return facts

    def _top_blocks(self):
        """Blocks NOT owned by a container op (the global block plus
        strays); container-owned blocks are walked from their op so
        guard stacks nest correctly."""
        owned = set()
        for blk, _ in iter_blocks(self.program):
            for op in blk.ops:
                for _, sub in iter_sub_blocks(op):
                    owned.add(id(sub))
        for blk, container in iter_blocks(self.program):
            if id(blk) not in owned:
                yield blk, container

    def _value_of(self, name: str, blk: Block) -> ValueFact:
        got = self.values.get(name)
        if got is not None:
            return got
        # unwritten names — persistables, data vars, and undeclared
        # feeds/companions alike — seed REPLICATED: the single-
        # logical-device runtime materializes one value for everyone,
        # and divergence must be proven positively through a marked
        # source (PTA001 flags genuinely missing names)
        return ValueFact(REPLICATED)

    def _set(self, name: str, fact: ValueFact):
        old = self.values.get(name)
        new = fact if old is None else old.joined(fact)
        if old != new:
            self.values[name] = new
            self.changed = True

    def _mark_active(self, op: Operator) -> bool:
        """Whether a divergence-source mark on `op` fires under this
        program's mesh: axes-qualified marks are inert when the
        attached MeshConfig has none of the named axes at size > 1
        (the predicate cannot vary across a mesh that lacks its
        axis); unqualified marks, or no mesh, stay active."""
        axes = op.attrs.get(DIVERGENCE_AXES_ATTR)
        if not axes or self.mesh is None:
            return True
        return any(self.mesh.size(str(a)) > 1 for a in axes)

    def _transfer(self, op: Operator, blk: Block,
                  site: OpSite) -> ValueFact:
        tag = op.attrs.get(DIVERGENCE_ATTR)
        if isinstance(tag, str) and tag and self._mark_active(op):
            return ValueFact(VARYING, tag, site.anchor(), manual=True)
        axes = op.attrs.get(SHARDING_ATTR)
        if axes:
            return ValueFact(VARYING, f"sharding:{tuple(axes)}",
                             site.anchor(), sharded=tuple(axes))
        if any(True for _ in iter_sub_blocks(op)):
            # container op: the body's writes land in the shared name
            # map during the sub-block walk, so joining every DATA
            # input here would smear e.g. a sharded loop input onto
            # the carried guard var and misclassify a genuinely
            # uniform loop as divergent. Only the guard's own
            # divergence flows onto the carried outputs (a value
            # whose definition depends on a divergent predicate is
            # divergent even if each branch writes uniformly).
            fact = ValueFact(REPLICATED)
            cond_slot = _COND_SLOTS.get(op.type)
            if cond_slot is not None:
                for n in op.inputs.get(cond_slot) or []:
                    if n != EMPTY_VAR:
                        fact = fact.joined(self._value_of(n, blk))
            return fact
        fact = ValueFact(REPLICATED)
        for n in op.input_arg_names:
            if n == EMPTY_VAR:
                continue
            fact = fact.joined(self._value_of(n, blk))
        return fact

    # --- the sharding-spec transfer ------------------------------------
    def _spec_of(self, name: str, blk: Block) -> ShardSpec:
        got = self.pins.get(name)
        if got is not None and not got.is_top:
            return got
        got = self.specs.get(name)
        if got is not None:
            return got
        if name in self.pins:           # legacy axes-only annotation
            return TOP_SPEC
        return REPLICATED_SPEC

    def _set_spec(self, name: str, spec: ShardSpec, site: OpSite,
                  guards) -> None:
        pin = self.pins.get(name)
        if pin is not None and not pin.is_top:
            # the annotation HOLDS (with_sharding_constraint): a
            # producer computing a different layout implies GSPMD
            # reshards at the write — record the fact, keep the pin
            if spec != pin and not spec.is_top:
                self.events.append(EventSite(site, guards, CollectiveEvent(
                    "reshard", spec.axes() + pin.axes(), name,
                    f"producer computes {spec.describe()} but "
                    f"{name!r} is pinned {pin.describe()}")))
            return
        old = self.specs.get(name)
        new = spec if old is None else spec_join(old, spec)
        if old != new:
            self.specs[name] = new
            self.changed = True

    def _transfer_specs(self, op: Operator, blk: Block, site: OpSite,
                        guards) -> None:
        from ..core.registry import get_sharding_rule

        if any(True for _ in iter_sub_blocks(op)):
            # container op: carried outputs are written BY the body
            # (walked into the same spec map), so there is nothing to
            # transfer here — and degrading them to ⊤ would clobber
            # the body-propagated layouts and emit a misleading
            # "register a rule for 'while'" warning
            return
        dims = op.attrs.get(SHARDING_DIMS_ATTR)
        if dims is not None:
            spec = ShardSpec.of(dims)
            for n in op.output_arg_names:
                if n != EMPTY_VAR:
                    self._set_spec(n, spec, site, guards)
            return

        def spec_of(name):
            return self._spec_of(name, blk)

        def shape_of(name):
            var = blk._find_var_recursive(name) \
                if blk is not None else None
            if var is None or var.shape is None:
                return None
            return tuple(var.shape)

        rule = get_sharding_rule(op.type)
        if rule is not None:
            out_specs, events = rule(op, spec_of, shape_of, self.mesh)
            for n, s in out_specs.items():
                self._set_spec(n, s, site, guards)
            for ev in events:
                self.events.append(EventSite(site, guards, ev))
            return
        # no rule: replicated-in -> replicated-out is sound (an
        # unannotated op cannot mint sharding); any sharded input
        # degrades every output to the explicit ⊤ spec, warn-once
        touched = [n for n in op.input_arg_names
                   if n != EMPTY_VAR
                   and not self._spec_of(n, blk).is_replicated]
        out = TOP_SPEC if touched else REPLICATED_SPEC
        if touched and op.type not in self._top_warned:
            self._top_warned.add(op.type)
            import warnings

            warnings.warn(
                f"sharding domain: op type {op.type!r} has no "
                f"registered sharding rule but consumes sharded "
                f"value(s) {touched[:3]}; its outputs degrade to the "
                f"⊤ spec. Register a rule via core.registry."
                f"register_sharding_rule (analysis/sharding_rules.py "
                f"has the families) or explicitly declare replication.")
        for n in op.output_arg_names:
            if n != EMPTY_VAR:
                self._set_spec(n, out, site, guards)

    # --- the ownership (index-provenance) transfer ----------------------
    # ops whose output still EXPOSES the pool's cells to a downstream
    # gather (value-preserving views and per-element copies): a miss
    # here would let a pool read escape PTA190 silently, so the set
    # over-approximates — slice/split narrow but still alias pool
    # rows, cast copies values 1:1
    _VIEW_OPS = frozenset({
        "reshape", "reshape2", "transpose", "transpose2",
        "unsqueeze", "unsqueeze2", "squeeze", "squeeze2",
        "slice", "split", "cast",
    })

    def _prov_of(self, name: str) -> Optional[ProvFact]:
        got = self.prov_pins.get(name)
        if got is not None:
            return got
        return self.prov.get(name)

    def _set_prov(self, name: str, fact: Optional[ProvFact]) -> None:
        if fact is None or name in self.prov_pins:
            return
        old = self.prov.get(name)
        new = fact if old is None else prov_join(old, fact)
        if old is not None and new.bound is not None and \
                (old.bound is None or new.bound > old.bound):
            # WIDENING: the bound lattice has infinite ascending
            # chains (a const-seeded RMW counter — assign(add(cnt,
            # 1), output=cnt) in a While — grows its bound by 1
            # every fixpoint iteration, to non-convergence at
            # _MAX_ITERS and a silently-disabled prover). A join
            # that GROWS an existing bound jumps straight to
            # unbounded; single-writer straight-line chains never
            # re-join and keep their exact bounds.
            new = ProvFact(new.tags, new.const, new.indicator,
                           new.onehot, new.selection, new.distinct,
                           None, new.nonneg, new.oh_tail, new.chain)
        if old != new:
            self.prov[name] = new
            self.changed = True

    def _is_pool(self, name: str, blk: Block) -> bool:
        if POOL_MARK not in name:
            return False
        var = blk._find_var_recursive(name)
        return var is None or bool(var.persistable)

    def _transfer_prov(self, op: Operator, blk: Block, site: OpSite,
                       guards) -> None:
        from ..core.registry import get_index_rule

        # mint site: a mark_pool_index_source'd producer
        tag = op.attrs.get(OWNERSHIP_ATTR)
        if isinstance(tag, str) and tag in _OWNERSHIP_SOURCES:
            src = _OWNERSHIP_SOURCES[tag]
            fact = ProvFact(
                tags=(tag,), indicator=src.indicator,
                bound=op.attrs.get(OWNERSHIP_BOUND_ATTR),
                chain=(f"{tag} mint at {site.anchor()}",))
            for n in op.output_arg_names:
                if n != EMPTY_VAR:
                    self._set_prov(n, fact)
            return
        rule = get_index_rule(op.type)
        if rule is not None:
            def shape_of(name):
                var = blk._find_var_recursive(name) \
                    if blk is not None else None
                if var is None or var.shape is None:
                    return None
                return tuple(var.shape)

            out = rule(op, self._prov_of, shape_of)
            for n, f in out.items():
                self._set_prov(n, f)
        # an op without a rule propagates NO provenance: its outputs
        # reach a @POOL access as unknown and PTA190 rejects there

    def _record_pool_access(self, op: Operator, blk: Block,
                            site: OpSite, guards) -> None:
        def _first(slot):
            names = op.inputs.get(slot) or []
            return names[0] if names and names[0] != EMPTY_VAR \
                else None

        if op.type == "masked_pool_write":
            pools = [n for n in op.output_arg_names
                     if self._is_pool(n, blk)]
            idx = _first("Index")
            gate = _first("Gate")
            for pool in pools:
                cells = None
                var = blk._find_var_recursive(pool)
                lead = op.attrs.get("leading_dims", 1)
                if var is not None and var.shape is not None and \
                        isinstance(lead, int) and \
                        0 < lead <= len(var.shape) and all(
                            d is not None and d >= 0
                            for d in var.shape[:lead]):
                    cells = 1
                    for d in var.shape[:lead]:
                        cells *= int(d)
                self.pool_accesses.append(PoolAccess(
                    site, guards, "write", pool, idx,
                    self._prov_of(idx) if idx else None, gate,
                    self._prov_of(gate) if gate else None,
                    axis_size=cells))
            return
        # any OTHER writer of a pool var (container ops surface their
        # sub-blocks' writes and are judged at the inner site)
        for n in op.output_arg_names:
            if self._is_pool(n, blk):
                self.pool_accesses.append(PoolAccess(
                    site, guards, "write", n, None, None))
        # view tracking + gather reads
        if op.type in self._VIEW_OPS:
            roots = [self.pool_views.get(n) or
                     (n if self._is_pool(n, blk) else None)
                     for n in op.input_arg_names if n != EMPTY_VAR]
            root = next((r for r in roots if r is not None), None)
            if root is not None:
                for n in op.output_arg_names:
                    if n != EMPTY_VAR:
                        self.pool_views[n] = root
            return
        if op.type in ("gather", "gather_nd"):
            x = _first("X")
            root = self.pool_views.get(x) if x else None
            if root is None and x and self._is_pool(x, blk):
                root = x
            if root is not None:
                idx = _first("Index")
                axis = None
                # gather_nd's last-axis index COMPONENTS address
                # multiple leading axes of X — a single scalar bound
                # cannot be compared against shape[0] (falsely
                # flags correct programs AND falsely passes a
                # too-big trailing component), so its axis stays
                # unknown and only provenance is judged
                xvar = blk._find_var_recursive(x) \
                    if x is not None else None
                if op.type == "gather" and xvar is not None and \
                        xvar.shape and xvar.shape[0] is not None \
                        and xvar.shape[0] >= 0:
                    axis = int(xvar.shape[0])
                self.pool_accesses.append(PoolAccess(
                    site, guards, "read", root, idx,
                    self._prov_of(idx) if idx else None,
                    axis_size=axis))

    def _walk(self, blk: Block, container: Optional[Operator],
              guard_stack: Tuple[GuardFact, ...]):
        for i, op in enumerate(blk.ops):
            site = OpSite(blk.idx, i, op, container)
            self.sites.append(site)
            if guard_stack:
                self.guards[id(op)] = guard_stack
            out_fact = self._transfer(op, blk, site)
            for n in op.output_arg_names:
                if n != EMPTY_VAR:
                    self._set(n, out_fact)
            subs = list(iter_sub_blocks(op))
            if op.type not in ("feed", "fetch"):
                self._transfer_specs(op, blk, site, guard_stack)
                if not subs:
                    self._transfer_prov(op, blk, site, guard_stack)
                    self._record_pool_access(op, blk, site,
                                             guard_stack)
            if not subs:
                continue
            inner = guard_stack
            cond_slot = _COND_SLOTS.get(op.type)
            if cond_slot is not None:
                cond_names = op.inputs.get(cond_slot) or []
                cond = cond_names[0] if cond_names else None
                cf = self._value_of(cond, blk) if cond else \
                    ValueFact(UNKNOWN)
                repl = cf.repl
                if repl == VARYING and not cf.manual \
                        and isinstance(cf.source, str) \
                        and cf.source.startswith("sharding:"):
                    # GSPMD-uniform guard: the predicate's only
                    # varying ancestry is auto-axis sharding
                    # annotations — under GSPMD SPMD semantics the
                    # partitioner computes predicates CONSISTENTLY
                    # on every device (it inserts whatever
                    # collectives the replicated cond needs, outside
                    # any manual divergence), so control flow stays
                    # uniform. Manual sources (pp_stage_id,
                    # mesh_coord, lane_active_mask under a lane-
                    # sharding mesh) never take this path: the
                    # STICKY ValueFact.manual bit survives joins, so
                    # a predicate MIXING sharded values with a
                    # manual source stays proven-divergent even when
                    # the surviving source string is "sharding:*".
                    repl = REPLICATED
                inner = guard_stack + (GuardFact(
                    op.type, site.anchor(), cond, repl,
                    cf.source, cf.minted_at),)
            for _, sub in subs:
                self._walk(sub, op, inner)


def analyze(program: Program) -> ProgramFacts:
    """Run (or fetch the cached) fixpoint analysis for `program`.
    The cache rides ON the program object (`_absint_cache`, keyed by
    `_version` — the `fingerprint()` caching pattern), so PTA130 and
    PTA131 share one run, Pass.apply's version bump invalidates it,
    and a dead Program frees its facts with itself: a global
    facts-by-uid map would pin every analyzed program's whole IR
    (blocks/vars/ops via the recorded OpSites) for the life of a
    serving process under model churn.

    Reference counterpart: reference framework/op_desc.cc CheckAttrs
    validates ONE op; a whole-program fixpoint over divergence/
    replication facts is the jit-era gate with no reference analogue.
    """
    version = getattr(program, "_version", 0)
    cached = getattr(program, "_absint_cache", None)
    if cached is not None and cached[0] == version:
        return cached[1]
    facts = _Interp(program).run()
    try:
        program._absint_cache = (version, facts)
    except AttributeError:
        pass  # exotic program-likes without attribute space
    return facts


# --- symbolic shape/dtype: declared-vs-producer disagreements ---------------
@dataclass(frozen=True)
class DeclClobber:
    """One var whose builder declaration was overwritten in place by
    build-time shape inference (core/registry.py stashes the
    original)."""
    block_idx: int
    name: str
    declared_shape: Optional[tuple]
    final_shape: Optional[tuple]
    declared_dtype: Optional[str]
    final_dtype: Optional[str]
    persistable: bool
    is_data: bool


def declared_clobbers(program: Program) -> List[DeclClobber]:
    """Every var carrying a stashed declaration that differs from its
    final (producer-inferred) shape/dtype, in block order.

    Reference counterpart: reference InferShape (framework/
    shape_inference.h) RAISES on declared-vs-inferred disagreement;
    the in-place Python IR overwrites instead, so the stash+sweep
    recovers the check the reference got for free.
    """
    out: List[DeclClobber] = []
    for blk, _ in iter_blocks(program):
        for name, var in blk.vars.items():
            ds = getattr(var, "_declared_shape", None)
            dd = getattr(var, "_declared_dtype", None)
            if ds is None and dd is None:
                continue
            final_shape = tuple(var.shape) if var.shape is not None \
                else None
            if ds is not None and final_shape == tuple(ds):
                ds = None  # converged back: not a clobber
            dtype_s = var.dtype.value if var.dtype is not None else None
            decl_dtype_s = dd.value if dd is not None else None
            if decl_dtype_s is not None and decl_dtype_s == dtype_s:
                decl_dtype_s = None
            if ds is None and decl_dtype_s is None:
                continue
            out.append(DeclClobber(
                blk.idx, name,
                tuple(ds) if ds is not None else None, final_shape,
                decl_dtype_s, dtype_s,
                bool(var.persistable), bool(var.is_data)))
    return out


def while_carried_names(program: Program) -> set:
    """Names carried through while/run_block_if loops anywhere in the
    program — the set whose dtype stability the lax.while_loop carry
    contract depends on (PTA020/PTA140).

    Reference counterpart: reference operators/controlflow/
    while_op_helper.cc skip-eager-deletion var lists — the carried
    set whose dtype/shape stability the loop depends on.
    """
    carried = set()
    for blk, _ in iter_blocks(program):
        for op in blk.ops:
            if op.type in ("while", "run_block_if"):
                names = op.attrs.get("carried")
                if isinstance(names, (list, tuple)):
                    carried.update(n for n in names
                                   if isinstance(n, str))
    return carried
