"""Abstract interpretation over the Program IR: the divergence &
sharding prover.

Reference counterpart: the reference validates every program in C++
before execution (reference paddle/fluid/framework/op_desc.cc
CheckAttrs/InferShape, operator.cc:975 RunImpl enforcement) but runs
control flow on the HOST, so "is this collective inside a divergent
branch" is not a question its validators can even ask. Here a whole
Block jits into ONE XLA computation and control flow traces into
lax.cond/lax.while_loop — a collective under a predicate that differs
across mesh coordinates deadlocks the chip (the r5 shard_map trap,
re-hit as 1F1B x tp; CLAUDE.md session learnings). The pattern
matcher (checkers.py PTA010/011) catches the lexical shape of that
bug; this module upgrades it to a PROOF: whole-program fixpoint
propagation over three abstract domains, so "this site executes
uniformly" and "this value is replicated across the mesh" become
checkable facts that PR 12's sharded serving lowerings can lean on.

Domains
-------
1. **Divergence contexts** — for every OpSite, the stack of guard
   predicates (while / conditional_block / run_block_if / ifelse
   conditions) the site executes under, each classified by the
   replication fact of its condition value.
2. **Replication lattice** — ``replicated ⊑ varying ⊑ unknown`` per
   value. Seeds: persistables, data vars and constants are
   `replicated` (the single-logical-device build); ops annotated with
   a registered *divergence source* (``divergence_source`` attr —
   lane active masks, pp stage ids, explicit `_vary` casts) or an
   auto-axis sharding annotation (``sharding_axes`` attr) mint
   `varying` values; joins propagate through assign/arith chains and
   through sub-blocks to a fixpoint.
3. **Symbolic shape/dtype** — build-time shape inference clobbers
   declared shapes in place (core/registry.py stashes the original as
   ``_declared_shape``/``_declared_dtype``); `declared_clobbers`
   surfaces declared-vs-producer disagreements (the r10 class) and
   int->float promotions (PTA020 generalized beyond `increment`).

Annotation surface (the seed table)
-----------------------------------
Builders that MINT a predicate that can differ across mesh
coordinates must mark the minting op::

    from paddle_tpu.analysis import absint
    cond = layers.greater_than(live, min_active)
    absint.mark_divergence_source(cond, "lane_active_mask")

New divergence sources (PR 12's sharded lowerings: dp lane shards,
tp/vocab shards) must register a tag first via
``register_divergence_source`` — `mark_divergence_source` refuses
unknown tags so the seed table stays the single source of truth.

Checkers PTA130/131 (checkers.py) read the facts computed here; the
engine itself is pure Python over Program metadata (no jax, no
tracing) and analyzes a whole model program in milliseconds.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.program import Block, Operator, Program
from ..core.registry import EMPTY_VAR
from .dataflow import OpSite, iter_blocks, iter_sub_blocks

__all__ = [
    "REPLICATED", "VARYING", "UNKNOWN", "join",
    "DIVERGENCE_ATTR", "SHARDING_ATTR",
    "register_divergence_source", "divergence_sources",
    "mark_divergence_source", "mark_sharded",
    "ValueFact", "GuardFact", "ProgramFacts", "analyze",
    "declared_clobbers",
]

# --- the replication lattice ------------------------------------------------
REPLICATED, VARYING, UNKNOWN = "replicated", "varying", "unknown"
_ORDER = {REPLICATED: 0, VARYING: 1, UNKNOWN: 2}


def join(a: str, b: str) -> str:
    """Least upper bound: replicated ⊑ varying ⊑ unknown.

    Reference counterpart: none — standard dataflow lattice join.
    """
    return a if _ORDER[a] >= _ORDER[b] else b


# --- annotation attrs & the divergence-source seed table --------------------
DIVERGENCE_ATTR = "divergence_source"
SHARDING_ATTR = "sharding_axes"

# tag -> human explanation of WHY values minted under it differ across
# mesh coordinates. This is the seed table the ISSUE/ROADMAP name: a
# new sharded lowering that mints a new predicate family registers its
# tag here (CLAUDE.md conventions) so the prover knows about it.
_DIVERGENCE_SOURCES: Dict[str, str] = {
    "lane_active_mask": (
        "per-lane active/finished masks: once decode lanes shard "
        "across a data-parallel mesh axis, each device sees only its "
        "own lanes' masks — burst-exit predicates derived from them "
        "differ per device"),
    "pp_stage_id": (
        "pipeline-stage coordinate: per-stage predicates (the 1F1B "
        "F/B selector) differ across pp mesh coordinates BY "
        "construction — the r5 deadlock family"),
    "mesh_coord": (
        "a mesh axis index (lax.axis_index analogue): differs across "
        "that axis by definition"),
    "vary": (
        "explicit replicated->varying cast done OUTSIDE divergent "
        "control flow (the r5 `_vary` fix): the value is per-device "
        "from here on, and its grad transpose psum lands at this op, "
        "not inside a branch"),
}


def register_divergence_source(tag: str, description: str) -> None:
    """Add a divergence-source tag to the seed table (idempotent for
    an identical description; refuses silent redefinition).

    Reference counterpart: none — the reference ran control flow on
    the host (reference operators/controlflow/while_op.cc), so a
    cross-device predicate-divergence registry had nothing to gate.
    """
    old = _DIVERGENCE_SOURCES.get(tag)
    if old is not None and old != description:
        raise ValueError(
            f"divergence source {tag!r} already registered with a "
            f"different description; pick a new tag")
    _DIVERGENCE_SOURCES[tag] = description


def divergence_sources() -> Dict[str, str]:
    """The registered seed table, copied. Reference counterpart:
    none (see register_divergence_source)."""
    return dict(_DIVERGENCE_SOURCES)


def _producer_op(var) -> Optional[Operator]:
    """Most recent op writing `var` (searched from the var's program,
    current block first — the helper is called right after the layer
    call appends the producer)."""
    name = getattr(var, "name", var)
    blk = getattr(var, "block", None)
    program = blk.program if blk is not None else None
    if program is None:
        return None
    blocks = [program.current_block()] + list(program.blocks)
    seen = set()
    for b in blocks:
        if id(b) in seen:
            continue
        seen.add(id(b))
        for op in reversed(b.ops):
            if name in op.output_arg_names:
                return op
    return None


def mark_divergence_source(var, tag: str) -> None:
    """Build-time annotation: mark the producer op of `var` as minting
    a mesh-varying value (tag must be in the registered seed table).
    The abstract interpreter seeds the replication lattice from these
    marks; collectives/grads guarded by values derived from them get
    PROVEN-divergent diagnostics (PTA130/131) instead of pattern
    guesses.

    Reference counterpart: none (see register_divergence_source);
    compile-time capability of the whole-block-jit executor.
    """
    if tag not in _DIVERGENCE_SOURCES:
        raise ValueError(
            f"unknown divergence source {tag!r}; register it first "
            f"(absint.register_divergence_source) — known: "
            f"{sorted(_DIVERGENCE_SOURCES)}")
    op = _producer_op(var)
    if op is None:
        raise ValueError(
            f"mark_divergence_source: no producer op found for "
            f"{getattr(var, 'name', var)!r}")
    op.attrs[DIVERGENCE_ATTR] = tag
    blk = getattr(var, "block", None)
    if blk is not None and blk.program is not None:
        blk.program._version += 1  # invalidate cached fingerprints/facts


def mark_sharded(var, axes) -> None:
    """Mark the producer of `var` as carrying an auto-axis sharding
    annotation (the with_sharding_constraint analogue PR 12's
    lowerings emit): GSPMD may insert collectives wherever the value
    is consumed, so the prover treats it as varying and PTA131 rejects
    reads of it inside divergent contexts.

    Reference counterpart: the reference annotated placement per op
    (reference framework/op_desc.cc device attrs); GSPMD auto-axis
    annotations whose collectives MOVE have no analogue there.
    """
    op = _producer_op(var)
    if op is None:
        raise ValueError(
            f"mark_sharded: no producer op found for "
            f"{getattr(var, 'name', var)!r}")
    op.attrs[SHARDING_ATTR] = tuple(axes) if isinstance(
        axes, (list, tuple)) else (axes,)
    blk = getattr(var, "block", None)
    if blk is not None and blk.program is not None:
        blk.program._version += 1


# --- facts ------------------------------------------------------------------
@dataclass(frozen=True)
class ValueFact:
    """Abstract value of one var name."""
    repl: str = REPLICATED          # REPLICATED | VARYING | UNKNOWN
    source: Optional[str] = None    # divergence tag when VARYING
    minted_at: Optional[str] = None  # anchor of the minting op
    sharded: Optional[tuple] = None  # sharding axes annotation, if any

    def joined(self, other: "ValueFact") -> "ValueFact":
        repl = join(self.repl, other.repl)
        # keep the explanation of whichever side made us varying
        lead = self if _ORDER[self.repl] >= _ORDER[other.repl] else other
        return ValueFact(repl, lead.source, lead.minted_at,
                         self.sharded or other.sharded)


@dataclass(frozen=True)
class GuardFact:
    """One divergent-control-flow predicate a site executes under."""
    container_type: str             # while / conditional_block / ...
    container_anchor: str           # OpSite.anchor() of the container
    cond_var: Optional[str]         # predicate var name
    fact: str                       # replication class of the predicate
    source: Optional[str] = None    # divergence tag when proven varying
    minted_at: Optional[str] = None

    def describe(self) -> str:
        what = f"{self.container_type} guard {self.cond_var!r}"
        if self.fact == VARYING:
            src = _DIVERGENCE_SOURCES.get(self.source or "", "")
            out = (f"{what}: PROVEN divergent across mesh coordinates "
                   f"(source {self.source!r}")
            if self.minted_at:
                out += f", minted at {self.minted_at}"
            out += ")"
            if src:
                out += f" — {src}"
            return out
        if self.fact == UNKNOWN:
            return (f"{what}: divergence UNPROVABLE (predicate derives "
                    f"from values outside the replication facts)")
        return (f"{what}: value-uniform under current replication "
                f"facts (facts assume unsharded feeds)")


@dataclass
class ProgramFacts:
    """Result of one fixpoint run over a Program."""
    program: Program
    values: Dict[str, ValueFact] = field(default_factory=dict)
    # id(op) -> guard stack (outermost first); only guarded ops appear
    _guards: Dict[int, Tuple[GuardFact, ...]] = field(
        default_factory=dict)
    # every site, recorded in walk order (guarded or not)
    sites: List[OpSite] = field(default_factory=list)
    iterations: int = 0
    converged: bool = True

    def value(self, name: str) -> ValueFact:
        return self.values.get(name, ValueFact(REPLICATED))

    def guards(self, op: Operator) -> Tuple[GuardFact, ...]:
        return self._guards.get(id(op), ())

    def guarded_sites(self) -> Iterable[Tuple[OpSite,
                                              Tuple[GuardFact, ...]]]:
        for site in self.sites:
            g = self._guards.get(id(site.op))
            if g:
                yield site, g

    def divergent(self, guards: Tuple[GuardFact, ...]) -> bool:
        return any(g.fact == VARYING for g in guards)

    def unproven(self, guards: Tuple[GuardFact, ...]) -> bool:
        return any(g.fact in (VARYING, UNKNOWN) for g in guards)


# container op type -> input slot holding the branch predicate
# (mirrors checkers.DIVERGENT_CONTAINERS; the kernels are in
# ops/control_flow_ops.py and ops/lod_ops.py)
_COND_SLOTS = {
    "while": "Condition",
    "run_block_if": "Condition",
    "conditional_block": "Condition",
    "ifelse": "Cond",
}

_MAX_ITERS = 16


class _Interp:
    """One fixpoint run. Values live in ONE name->fact map: var names
    are program-unique in practice (sub-block kernels resolve parent
    names by identity), and the join makes any accidental collision
    err toward varying/unknown — conservative, never silently
    uniform."""

    def __init__(self, program: Program):
        self.program = program
        self.values: Dict[str, ValueFact] = {}
        self.guards: Dict[int, Tuple[GuardFact, ...]] = {}
        self.sites: List[OpSite] = []
        self.changed = False

    def run(self) -> ProgramFacts:
        iters = 0
        converged = False
        for iters in range(1, _MAX_ITERS + 1):
            self.changed = False
            self.guards.clear()
            self.sites = []
            for blk, container in self._top_blocks():
                self._walk(blk, container, ())
            if not self.changed:
                converged = True
                break
        facts = ProgramFacts(self.program, dict(self.values),
                             dict(self.guards), list(self.sites),
                             iterations=iters, converged=converged)
        return facts

    def _top_blocks(self):
        """Blocks NOT owned by a container op (the global block plus
        strays); container-owned blocks are walked from their op so
        guard stacks nest correctly."""
        owned = set()
        for blk, _ in iter_blocks(self.program):
            for op in blk.ops:
                for _, sub in iter_sub_blocks(op):
                    owned.add(id(sub))
        for blk, container in iter_blocks(self.program):
            if id(blk) not in owned:
                yield blk, container

    def _value_of(self, name: str, blk: Block) -> ValueFact:
        got = self.values.get(name)
        if got is not None:
            return got
        # unwritten names — persistables, data vars, and undeclared
        # feeds/companions alike — seed REPLICATED: the single-
        # logical-device runtime materializes one value for everyone,
        # and divergence must be proven positively through a marked
        # source (PTA001 flags genuinely missing names)
        return ValueFact(REPLICATED)

    def _set(self, name: str, fact: ValueFact):
        old = self.values.get(name)
        new = fact if old is None else old.joined(fact)
        if old != new:
            self.values[name] = new
            self.changed = True

    def _transfer(self, op: Operator, blk: Block,
                  site: OpSite) -> ValueFact:
        tag = op.attrs.get(DIVERGENCE_ATTR)
        if isinstance(tag, str) and tag:
            return ValueFact(VARYING, tag, site.anchor())
        axes = op.attrs.get(SHARDING_ATTR)
        if axes:
            return ValueFact(VARYING, f"sharding:{tuple(axes)}",
                             site.anchor(), sharded=tuple(axes))
        fact = ValueFact(REPLICATED)
        for n in op.input_arg_names:
            if n == EMPTY_VAR:
                continue
            fact = fact.joined(self._value_of(n, blk))
        return fact

    def _walk(self, blk: Block, container: Optional[Operator],
              guard_stack: Tuple[GuardFact, ...]):
        for i, op in enumerate(blk.ops):
            site = OpSite(blk.idx, i, op, container)
            self.sites.append(site)
            if guard_stack:
                self.guards[id(op)] = guard_stack
            out_fact = self._transfer(op, blk, site)
            for n in op.output_arg_names:
                if n != EMPTY_VAR:
                    self._set(n, out_fact)
            subs = list(iter_sub_blocks(op))
            if not subs:
                continue
            inner = guard_stack
            cond_slot = _COND_SLOTS.get(op.type)
            if cond_slot is not None:
                cond_names = op.inputs.get(cond_slot) or []
                cond = cond_names[0] if cond_names else None
                cf = self._value_of(cond, blk) if cond else \
                    ValueFact(UNKNOWN)
                inner = guard_stack + (GuardFact(
                    op.type, site.anchor(), cond, cf.repl,
                    cf.source, cf.minted_at),)
            for _, sub in subs:
                self._walk(sub, op, inner)


def analyze(program: Program) -> ProgramFacts:
    """Run (or fetch the cached) fixpoint analysis for `program`.
    The cache rides ON the program object (`_absint_cache`, keyed by
    `_version` — the `fingerprint()` caching pattern), so PTA130 and
    PTA131 share one run, Pass.apply's version bump invalidates it,
    and a dead Program frees its facts with itself: a global
    facts-by-uid map would pin every analyzed program's whole IR
    (blocks/vars/ops via the recorded OpSites) for the life of a
    serving process under model churn.

    Reference counterpart: reference framework/op_desc.cc CheckAttrs
    validates ONE op; a whole-program fixpoint over divergence/
    replication facts is the jit-era gate with no reference analogue.
    """
    version = getattr(program, "_version", 0)
    cached = getattr(program, "_absint_cache", None)
    if cached is not None and cached[0] == version:
        return cached[1]
    facts = _Interp(program).run()
    try:
        program._absint_cache = (version, facts)
    except AttributeError:
        pass  # exotic program-likes without attribute space
    return facts


# --- symbolic shape/dtype: declared-vs-producer disagreements ---------------
@dataclass(frozen=True)
class DeclClobber:
    """One var whose builder declaration was overwritten in place by
    build-time shape inference (core/registry.py stashes the
    original)."""
    block_idx: int
    name: str
    declared_shape: Optional[tuple]
    final_shape: Optional[tuple]
    declared_dtype: Optional[str]
    final_dtype: Optional[str]
    persistable: bool
    is_data: bool


def declared_clobbers(program: Program) -> List[DeclClobber]:
    """Every var carrying a stashed declaration that differs from its
    final (producer-inferred) shape/dtype, in block order.

    Reference counterpart: reference InferShape (framework/
    shape_inference.h) RAISES on declared-vs-inferred disagreement;
    the in-place Python IR overwrites instead, so the stash+sweep
    recovers the check the reference got for free.
    """
    out: List[DeclClobber] = []
    for blk, _ in iter_blocks(program):
        for name, var in blk.vars.items():
            ds = getattr(var, "_declared_shape", None)
            dd = getattr(var, "_declared_dtype", None)
            if ds is None and dd is None:
                continue
            final_shape = tuple(var.shape) if var.shape is not None \
                else None
            if ds is not None and final_shape == tuple(ds):
                ds = None  # converged back: not a clobber
            dtype_s = var.dtype.value if var.dtype is not None else None
            decl_dtype_s = dd.value if dd is not None else None
            if decl_dtype_s is not None and decl_dtype_s == dtype_s:
                decl_dtype_s = None
            if ds is None and decl_dtype_s is None:
                continue
            out.append(DeclClobber(
                blk.idx, name,
                tuple(ds) if ds is not None else None, final_shape,
                decl_dtype_s, dtype_s,
                bool(var.persistable), bool(var.is_data)))
    return out


def while_carried_names(program: Program) -> set:
    """Names carried through while/run_block_if loops anywhere in the
    program — the set whose dtype stability the lax.while_loop carry
    contract depends on (PTA020/PTA140).

    Reference counterpart: reference operators/controlflow/
    while_op_helper.cc skip-eager-deletion var lists — the carried
    set whose dtype/shape stability the loop depends on.
    """
    carried = set()
    for blk, _ in iter_blocks(program):
        for op in blk.ops:
            if op.type in ("while", "run_block_if"):
                names = op.attrs.get("carried")
                if isinstance(names, (list, tuple)):
                    carried.update(n for n in names
                                   if isinstance(n, str))
    return carried
