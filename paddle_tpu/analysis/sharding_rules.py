"""Sharding-propagation rules for the core op families.

GSPMD-style spec propagation (Xu et al., "GSPMD: General and Scalable
Parallelization for ML Computation Graphs") over the Program IR: each
rule states how one op family carries a {tensor dim -> mesh axis}
placement from inputs to outputs, and which collectives its XLA
lowering IMPLIES under those placements (a matmul contracting a
sharded dim is a partial-sum + psum; a reduce over a sharded dim is a
psum; a reshape that breaks a sharded dim forces a GSPMD reshard).
The abstract interpreter (analysis/absint.py) runs these rules to the
same fixpoint as the divergence domain; the PTA160/161 provers and
the PTA170 per-device memory planner read the resulting facts.

Rules register through ``core.registry.register_sharding_rule`` —
alongside the kernels they describe — so adding an op that touches
sharded state means adding its propagation fact in the same place
(CLAUDE.md conventions). Ops WITHOUT a rule degrade to the explicit
⊤ spec (warn-once in absint) the moment a sharded value reaches
them: imprecision is visible, never silently wrong.

Rule contract::

    rule(op, spec_of, shape_of, mesh) -> (out_specs, events)

* ``spec_of(name) -> ShardSpec``, ``shape_of(name) -> tuple | None``
* ``out_specs``: {output var name -> ShardSpec}
* ``events``: [CollectiveEvent] the lowering implies at this site

Rules are PURE metadata functions: no jax, no tracing — the whole
zoo propagates in milliseconds.

Reference counterpart: none — the reference sharded at runtime via
transpilers (reference transpiler/distribute_transpiler.py); the
compile-time layout algebra is the Megatron-LM / GSPMD capability.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..core.registry import EMPTY_VAR, register_sharding_rule
from .absint import (REPLICATED_SPEC, TOP_SPEC, CollectiveEvent,
                     ShardSpec, spec_join)

__all__ = ["RULE_FAMILIES"]

# family name -> op types it covers (documentation + the property
# tests' enumeration; the actual registry is core.registry's)
RULE_FAMILIES: Dict[str, Tuple[str, ...]] = {}


def _family(name, op_types):
    RULE_FAMILIES[name] = tuple(op_types)

    def deco(fn):
        register_sharding_rule(op_types, fn)
        return fn

    return deco


def _outs(op):
    return [n for n in op.output_arg_names if n != EMPTY_VAR]


def _in(op, slot, idx=0):
    names = op.inputs.get(slot) or []
    return names[idx] if len(names) > idx else None


def _shift(spec: ShardSpec, delta: int, start: int = 0) -> ShardSpec:
    """Shift placement dims >= start by delta (unsqueeze/reduce)."""
    if spec.placements is None:
        return spec
    return ShardSpec.of([(d + delta if d >= start else d, a)
                         for d, a in spec.placements])


def _all_outs(op, spec, events=()):
    return {n: spec for n in _outs(op)}, list(events)


# ---------------------------------------------------------------------------
# elementwise / identity family: layout passes straight through
# ---------------------------------------------------------------------------
@_family("identity", (
    "assign", "cast", "scale", "relu", "sigmoid", "tanh", "exp",
    "log", "sqrt", "square", "abs", "clip", "dropout", "increment",
    "brelu", "elu", "leaky_relu", "relu6", "softsign", "softplus",
    "gelu", "fill_zeros_like", "fill_any_like", "sign", "floor",
    "ceil", "round", "reciprocal", "logical_not", "optimization_barrier",
))
def rule_identity(op, spec_of, shape_of, mesh):
    src = _in(op, "X") or (op.input_arg_names[:1] or [None])[0]
    spec = spec_of(src) if src and src != EMPTY_VAR else REPLICATED_SPEC
    return _all_outs(op, spec)


@_family("elementwise", (
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_min", "elementwise_max",
    "elementwise_pow", "elementwise_mod", "elementwise_floordiv",
    "equal", "not_equal", "greater_than", "greater_equal",
    "less_than", "less_equal", "logical_and", "logical_or",
))
def rule_elementwise(op, spec_of, shape_of, mesh):
    """Binary elementwise with the fluid `axis` broadcast: Y's dims
    align into X at offset `axis` (default: trailing). Two full-rank
    operands demanding different placements is a sharding
    CONTRADICTION — GSPMD must reshard one side at this site."""
    x, y = _in(op, "X"), _in(op, "Y")
    sx = spec_of(x) if x else REPLICATED_SPEC
    sy = spec_of(y) if y else REPLICATED_SPEC
    if sx.is_top or sy.is_top:
        return _all_outs(op, TOP_SPEC)
    shx, shy = shape_of(x) if x else None, shape_of(y) if y else None
    if shx is not None and shy is not None and len(shy) < len(shx):
        axis = op.attrs.get("axis", -1)
        off = len(shx) - len(shy) if axis in (-1, None) else int(axis)
        sy = _shift(sy, off)
    if sy.is_replicated or sx == sy:
        return _all_outs(op, sx)
    if sx.is_replicated:
        return _all_outs(op, sy)
    ev = CollectiveEvent(
        "conflict", tuple(sx.axes()) + tuple(sy.axes()),
        _outs(op)[0] if _outs(op) else None,
        f"elementwise operands demand incompatible specs "
        f"{sx.describe()} vs {sy.describe()}: GSPMD reshards one "
        f"side at this site")
    return _all_outs(op, TOP_SPEC, [ev])


@_family("sum", ("sum",))
def rule_sum(op, spec_of, shape_of, mesh):
    specs = [spec_of(n) for n in op.inputs.get("X", [])
             if n != EMPTY_VAR]
    if not specs:
        return _all_outs(op, REPLICATED_SPEC)
    out = specs[0]
    for s in specs[1:]:
        if s != out and not s.is_replicated and not out.is_replicated:
            ev = CollectiveEvent(
                "conflict", tuple(out.axes()) + tuple(s.axes()),
                _outs(op)[0] if _outs(op) else None,
                f"sum operands demand incompatible specs "
                f"{out.describe()} vs {s.describe()}")
            return _all_outs(op, TOP_SPEC, [ev])
        out = s if out.is_replicated else out
    return _all_outs(op, out)


# ---------------------------------------------------------------------------
# contraction family: mul (the fc matmul) and matmul
# ---------------------------------------------------------------------------
def _contraction(out_var, keep_a, keep_b, contracted, why):
    """Shared tail: psum event iff any contracted placement exists."""
    events = []
    if contracted:
        events.append(CollectiveEvent(
            "psum", tuple(sorted({a for a in contracted})), out_var,
            why))
    return events


@_family("mul", ("mul",))
def rule_mul(op, spec_of, shape_of, mesh):
    """The fc matmul: X flattens to [prod(:p), prod(p:)], Y to
    [prod(:q), prod(q:)] (p = x_num_col_dims, q = y_num_col_dims);
    out rank = p + (rank_y - q). Sharded contraction dims (X dims
    >= p, Y dims < q) are Megatron row-parallel: each device holds a
    partial product and the lowering implies a psum over the
    contraction axes."""
    x, y = _in(op, "X"), _in(op, "Y")
    sx, sy = spec_of(x), spec_of(y)
    if sx.is_top or sy.is_top:
        return _all_outs(op, TOP_SPEC)
    p = int(op.attrs.get("x_num_col_dims", 1))
    q = int(op.attrs.get("y_num_col_dims", 1))
    shy = shape_of(y)
    rank_y = len(shy) if shy is not None else 2
    out_places = []
    contracted = []
    for d, a in (sx.placements or ()):
        if d < p:
            out_places.append((d, a))
        else:
            contracted.append(a)
    for d, a in (sy.placements or ()):
        if d < q:
            contracted.append(a)
        else:
            out_places.append((p + d - q, a))
    out = _outs(op)
    events = _contraction(
        out[0] if out else None, None, None, contracted,
        "matmul contracts a sharded dim: each device holds a partial "
        "product; the lowering implies a psum over the contraction "
        "axes (Megatron row-parallel)")
    return {n: ShardSpec.of(out_places) for n in out}, events


@_family("matmul", ("matmul",))
def rule_matmul(op, spec_of, shape_of, mesh):
    """Batched matmul [..., m, k] x [..., k, n] (transpose_x/y
    attrs): batch placements carry from X, m from X, n from Y;
    a sharded k implies a psum."""
    x, y = _in(op, "X"), _in(op, "Y")
    sx, sy = spec_of(x), spec_of(y)
    if sx.is_top or sy.is_top:
        return _all_outs(op, TOP_SPEC)
    shx, shy = shape_of(x), shape_of(y)
    if shx is None or shy is None:
        if sx.is_replicated and sy.is_replicated:
            return _all_outs(op, REPLICATED_SPEC)
        return _all_outs(op, TOP_SPEC)
    rx, ry = len(shx), len(shy)
    tx = bool(op.attrs.get("transpose_x", False))
    ty = bool(op.attrs.get("transpose_y", False))
    xm, xk = (rx - 1, rx - 2) if tx else (rx - 2, rx - 1)
    yk, yn = (ry - 1, ry - 2) if ty else (ry - 2, ry - 1)
    out_rank = max(rx, ry)
    out_places = []
    contracted = []
    for d, a in (sx.placements or ()):
        if d == xk:
            contracted.append(a)
        elif d == xm:
            out_places.append((out_rank - 2, a))
        elif d < rx - 2:
            out_places.append((d + (out_rank - rx), a))
    for d, a in (sy.placements or ()):
        if d == yk:
            contracted.append(a)
        elif d == yn:
            out_places.append((out_rank - 1, a))
        elif d < ry - 2:
            dd = d + (out_rank - ry)
            if all(od != dd for od, _ in out_places):
                out_places.append((dd, a))
    out = _outs(op)
    events = _contraction(
        out[0] if out else None, None, None, contracted,
        "matmul contracts a sharded dim: each device holds a partial "
        "product; the lowering implies a psum over the contraction "
        "axes")
    # two batch placements landing on one out dim would have
    # collided above (first-wins); a genuine disagreement surfaces
    # as an elementwise conflict downstream
    return {n: ShardSpec.of(out_places) for n in out}, events


# ---------------------------------------------------------------------------
# layout movers: transpose / reshape / squeeze / unsqueeze / expand
# ---------------------------------------------------------------------------
@_family("transpose", ("transpose", "transpose2"))
def rule_transpose(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top or sx.is_replicated:
        return _all_outs(op, sx)
    perm = op.attrs.get("perm") or op.attrs.get("axis")
    if not perm:
        return _all_outs(op, TOP_SPEC)
    perm = [int(p) for p in perm]
    out_places = []
    for d, a in sx.placements:
        if d in perm:
            out_places.append((perm.index(d), a))
    return _all_outs(op, ShardSpec.of(out_places))


def _reshape_groups(in_shape, out_shape):
    """Greedy factorization of a reshape into (in_dims, out_dims)
    groups with equal products; None when the shapes do not factor
    cleanly (dynamic dims, -1, non-matching products)."""
    if any(d is None or d < 0 for d in in_shape) or \
            any(d is None or d < 0 for d in out_shape):
        return None
    groups = []
    i = j = 0
    while i < len(in_shape) or j < len(out_shape):
        gi, gj = [i], [j]
        pi = in_shape[i] if i < len(in_shape) else 1
        pj = out_shape[j] if j < len(out_shape) else 1
        while pi != pj:
            if pi < pj and gi[-1] + 1 < len(in_shape):
                gi.append(gi[-1] + 1)
                pi *= in_shape[gi[-1]]
            elif pj < pi and gj[-1] + 1 < len(out_shape):
                gj.append(gj[-1] + 1)
                pj *= out_shape[gj[-1]]
            else:
                return None
        # absorb trailing 1s so indices advance
        groups.append((gi, gj))
        i, j = gi[-1] + 1, gj[-1] + 1
    return groups


@_family("reshape", ("reshape", "reshape2"))
def rule_reshape(op, spec_of, shape_of, mesh):
    """A placement survives a reshape when its dim maps 1:1, or when
    it rides the MAJOR dim of a clean split/merge group whose size
    the mesh axis still divides (GSPMD's divisibility condition).
    Anything else is a forced reshard — the r5 'dp on the
    pre-reshape dim' family."""
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top or sx.is_replicated:
        return _all_outs(op, sx)
    in_shape = shape_of(x)
    out_names = _outs(op)
    out_shape = shape_of(out_names[0]) if out_names else None
    if in_shape is None or out_shape is None:
        return _all_outs(op, TOP_SPEC)
    groups = _reshape_groups(in_shape, out_shape)
    if groups is None:
        ev = CollectiveEvent(
            "reshard", sx.axes(), out_names[0] if out_names else None,
            f"reshape {in_shape}->{out_shape} does not factor; the "
            f"sharded layout {sx.describe()} cannot carry through")
        return _all_outs(op, TOP_SPEC, [ev])
    out_places = []
    events = []
    for d, a in sx.placements:
        grp = next((g for g in groups if d in g[0]), None)
        if grp is None:
            continue
        gi, gj = grp
        major_in, major_out = gi[0], gj[0]
        size = mesh.size(a) if mesh is not None else None
        carries = (d == major_in) and (
            size is None or out_shape[major_out] % size == 0)
        if len(gi) == 1 and len(gj) == 1:
            out_places.append((gj[0], a))
        elif carries:
            out_places.append((major_out, a))
        else:
            events.append(CollectiveEvent(
                "reshard", (a,),
                out_names[0] if out_names else None,
                f"reshape {in_shape}->{out_shape} splits/merges the "
                f"{a}-sharded dim {d} off the major position: GSPMD "
                f"must reshard (the r5 pre-reshape-dim trap)"))
    return _all_outs(op, ShardSpec.of(out_places), events)


@_family("unsqueeze", ("unsqueeze", "unsqueeze2"))
def rule_unsqueeze(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top or sx.is_replicated:
        return _all_outs(op, sx)
    axes = sorted(int(a) for a in (op.attrs.get("axes") or []))
    for pos in axes:
        sx = _shift(sx, 1, start=pos)
    return _all_outs(op, sx)


@_family("squeeze", ("squeeze", "squeeze2"))
def rule_squeeze(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top or sx.is_replicated:
        return _all_outs(op, sx)
    axes = sorted((int(a) for a in (op.attrs.get("axes") or [])),
                  reverse=True)
    for pos in axes:
        if sx.axis_of(pos) is not None:
            return _all_outs(op, TOP_SPEC)  # squeezing a sharded dim
        # the squeezed position itself is unsharded (checked above),
        # so shifting higher dims down is the whole story — a
        # placement landing ON pos after the shift is dim pos+1's,
        # legitimately renumbered
        sx = _shift(sx, -1, start=pos + 1)
    return _all_outs(op, sx)


@_family("expand", ("expand",))
def rule_expand(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top or sx.is_replicated:
        return _all_outs(op, sx)
    times = [int(t) for t in (op.attrs.get("expand_times") or [])]
    out_places = []
    events = []
    for d, a in sx.placements:
        if d < len(times) and times[d] != 1:
            events.append(CollectiveEvent(
                "reshard", (a,), _outs(op)[0] if _outs(op) else None,
                f"expand tiles the {a}-sharded dim {d}: the tiled "
                f"layout needs an allgather/reshard"))
        else:
            out_places.append((d, a))
    return _all_outs(op, ShardSpec.of(out_places), events)


# ---------------------------------------------------------------------------
# reductions & normalizations
# ---------------------------------------------------------------------------
def _reduce_places(spec, dims, rank, keep_dim):
    dropped_axes = []
    out_places = []
    dimset = {d % rank for d in dims}
    for d, a in (spec.placements or ()):
        if d in dimset:
            dropped_axes.append(a)
        elif keep_dim:
            out_places.append((d, a))
        else:
            out_places.append((d - sum(1 for r in dimset if r < d), a))
    return out_places, dropped_axes


@_family("reduce", ("reduce_sum", "reduce_mean", "reduce_max",
                    "reduce_min", "reduce_prod", "frobenius_norm"))
def rule_reduce(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top:
        return _all_outs(op, TOP_SPEC)
    if sx.is_replicated:
        return _all_outs(op, REPLICATED_SPEC)
    shape = shape_of(x)
    rank = len(shape) if shape is not None else None
    dims = op.attrs.get("dim")
    if op.attrs.get("reduce_all") or dims is None:
        dims = list(range(rank)) if rank is not None else None
    elif isinstance(dims, int):
        dims = [dims]
    if rank is None or dims is None:
        return _all_outs(op, TOP_SPEC)
    keep = bool(op.attrs.get("keep_dim", False))
    out_places, dropped = _reduce_places(sx, dims, rank, keep)
    events = []
    if dropped:
        events.append(CollectiveEvent(
            "psum", tuple(sorted(set(dropped))),
            _outs(op)[0] if _outs(op) else None,
            f"{op.type} reduces over dim(s) sharded on "
            f"{sorted(set(dropped))}: the lowering implies a psum "
            f"over those mesh axes"))
    return _all_outs(op, ShardSpec.of(out_places), events)


@_family("argminmax", ("arg_max", "arg_min", "argmax", "argmin"))
def rule_argminmax(op, spec_of, shape_of, mesh):
    """Arg-reduce over a sharded dim (the vocab-parallel argmax of a
    tp-sharded logits row): each device knows only its shard's
    winner; the lowering implies a cross-shard select (allgather/
    psum-of-max in Megatron's vocab-parallel head)."""
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top:
        return _all_outs(op, TOP_SPEC)
    if sx.is_replicated:
        return _all_outs(op, REPLICATED_SPEC)
    shape = shape_of(x)
    rank = len(shape) if shape is not None else None
    axis = op.attrs.get("axis", -1)
    if rank is None:
        return _all_outs(op, TOP_SPEC)
    axis = int(axis) % rank
    events = []
    a = sx.axis_of(axis)
    if a is not None:
        events.append(CollectiveEvent(
            "allgather", (a,), _outs(op)[0] if _outs(op) else None,
            f"arg-reduce over the {a}-sharded dim {axis}: each "
            f"device holds only its shard's winner — the lowering "
            f"implies a cross-shard select over {a!r}"))
    out_places, _ = _reduce_places(sx, [axis], rank, False)
    return _all_outs(op, ShardSpec.of(out_places), events)


@_family("mean", ("mean",))
def rule_mean(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    events = []
    if not sx.is_replicated and not sx.is_top:
        events.append(CollectiveEvent(
            "psum", tuple(sorted(set(sx.axes()))),
            _outs(op)[0] if _outs(op) else None,
            "global mean of a sharded value implies a psum"))
    return _all_outs(op, REPLICATED_SPEC, events)


@_family("softmax", ("softmax", "filtered_softmax"))
def rule_softmax(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top:
        return _all_outs(op, TOP_SPEC)
    axis = int(op.attrs.get("axis", -1))
    shape = shape_of(x)
    rank = len(shape) if shape is not None else None
    events = []
    if rank is not None:
        a = sx.axis_of(axis % rank)
        if a is not None:
            events.append(CollectiveEvent(
                "psum", (a,), _outs(op)[0] if _outs(op) else None,
                f"softmax normalizes over the {a}-sharded dim: the "
                f"max/sum reductions imply psums over {a!r}"))
    return _all_outs(op, sx, events)


@_family("layer_norm", ("layer_norm",))
def rule_layer_norm(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top:
        return _all_outs(op, TOP_SPEC)
    begin = int(op.attrs.get("begin_norm_axis", 1))
    events = []
    norm_axes = sorted({a for d, a in (sx.placements or ())
                        if d >= begin})
    if norm_axes:
        events.append(CollectiveEvent(
            "psum", tuple(norm_axes),
            _outs(op)[0] if _outs(op) else None,
            f"layer_norm's mean/variance reduce over dims sharded on "
            f"{norm_axes}: the lowering implies psums"))
    # Y keeps the input layout; Mean/Variance side outputs are
    # reductions — rank-agnostic REPLICATED is the safe spec for them
    outs = {}
    for slot, names in op.outputs.items():
        for n in names:
            if n == EMPTY_VAR:
                continue
            outs[n] = sx if slot == "Y" else REPLICATED_SPEC
    return outs, events


# ---------------------------------------------------------------------------
# concat / split / gather / scatter / one-hot families
# ---------------------------------------------------------------------------
@_family("concat", ("concat",))
def rule_concat(op, spec_of, shape_of, mesh):
    names = [n for n in op.inputs.get("X", []) if n != EMPTY_VAR]
    specs = [spec_of(n) for n in names]
    if any(s.is_top for s in specs):
        return _all_outs(op, TOP_SPEC)
    axis = int(op.attrs.get("axis", 0))
    events = []
    out = REPLICATED_SPEC
    for n, s in zip(names, specs):
        if s.axis_of(axis) is not None:
            events.append(CollectiveEvent(
                "reshard", (s.axis_of(axis),),
                _outs(op)[0] if _outs(op) else None,
                f"concat along the {s.axis_of(axis)}-sharded dim "
                f"{axis} of {n!r} forces a reshard"))
            s = ShardSpec.of([(d, a) for d, a in s.placements
                              if d != axis])
        out = s if out.is_replicated else out
        if not s.is_replicated and s != out:
            return _all_outs(op, TOP_SPEC, events + [CollectiveEvent(
                "conflict", tuple(out.axes()) + tuple(s.axes()),
                _outs(op)[0] if _outs(op) else None,
                f"concat operands demand incompatible specs "
                f"{out.describe()} vs {s.describe()}")])
    return _all_outs(op, out, events)


@_family("split", ("split",))
def rule_split(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top or sx.is_replicated:
        return _all_outs(op, sx)
    axis = int(op.attrs.get("dim", op.attrs.get("axis", 0)))
    events = []
    a = sx.axis_of(axis)
    if a is not None:
        events.append(CollectiveEvent(
            "reshard", (a,), _outs(op)[0] if _outs(op) else None,
            f"split along the {a}-sharded dim {axis} forces a "
            f"reshard"))
        sx = ShardSpec.of([(d, ax) for d, ax in sx.placements
                           if d != axis])
    return _all_outs(op, sx, events)


@_family("gather", ("gather", "lookup_table"))
def rule_gather(op, spec_of, shape_of, mesh):
    """Row gather (and the embedding lookup): a table sharded on the
    gathered dim 0 means every device holds only a vocab/row shard —
    the lowering one-hots + psums (or allgathers) across that axis.
    Trailing table dims carry their placements into the output's
    trailing dims; index placements carry into the leading dims."""
    table = _in(op, "W") or _in(op, "X")
    ids = _in(op, "Ids") or _in(op, "Index")
    st = spec_of(table) if table else REPLICATED_SPEC
    si = spec_of(ids) if ids else REPLICATED_SPEC
    if st.is_top or si.is_top:
        return _all_outs(op, TOP_SPEC)
    out_names = _outs(op)
    out_shape = shape_of(out_names[0]) if out_names else None
    tshape = shape_of(table) if table else None
    if out_shape is None or tshape is None:
        if st.is_replicated and si.is_replicated:
            return _all_outs(op, REPLICATED_SPEC)
        return _all_outs(op, TOP_SPEC)
    out_rank, trank = len(out_shape), len(tshape)
    lead = out_rank - (trank - 1)   # dims coming from the index
    events = []
    out_places = []
    if st.axis_of(0) is not None:
        events.append(CollectiveEvent(
            "allgather", (st.axis_of(0),),
            out_names[0] if out_names else None,
            f"gather from a table sharded on the gathered dim "
            f"(axis {st.axis_of(0)!r}): the lowering one-hots and "
            f"psums/allgathers across that axis"))
    for d, a in st.placements:
        if d >= 1 and lead + d - 1 >= 0:
            out_places.append((lead + d - 1, a))
    for d, a in si.placements:
        if d < lead:
            out_places.append((d, a))
    return _all_outs(op, ShardSpec.of(out_places), events)


@_family("one_hot", ("one_hot",))
def rule_one_hot(op, spec_of, shape_of, mesh):
    x = _in(op, "X")
    sx = spec_of(x)
    if sx.is_top:
        return _all_outs(op, TOP_SPEC)
    return _all_outs(op, sx)  # new trailing depth dim: replicated


@_family("pool_scatter", ("masked_pool_write", "span_scatter"))
def rule_pool_scatter(op, spec_of, shape_of, mesh):
    """One-hot-scatter state writers: the written buffer keeps ITS
    layout (the write is elementwise in the pool's own space); a New
    value laid out differently from the pool's trailing dims would
    need a reshard on the way in — surfaced as an event, the pool
    spec stays authoritative."""
    pool = _in(op, "Pool") or _in(op, "X")
    sp = spec_of(pool) if pool else REPLICATED_SPEC
    events = []
    new = _in(op, "New") or _in(op, "Vals")
    if new is not None and pool is not None:
        sn = spec_of(new)
        pshape, nshape = shape_of(pool), shape_of(new)
        if not sn.is_top and not sp.is_top and \
                pshape is not None and nshape is not None:
            off = len(pshape) - len(nshape)
            want = ShardSpec.of([(d - off, a)
                                 for d, a in sp.placements
                                 if d - off >= 0])
            if sn != want and not sn.is_replicated:
                events.append(CollectiveEvent(
                    "reshard", tuple(sn.axes()),
                    pool,
                    f"scatter source {new!r} is laid out "
                    f"{sn.describe()} but the pool's trailing dims "
                    f"demand {want.describe()}"))
    return _all_outs(op, sp, events)


@_family("sample", ("sample_categorical",))
def rule_sample_categorical(op, spec_of, shape_of, mesh):
    """Categorical draw over the last (vocab) axis: the sampled token
    ids are REPLICATED (one logical draw per lane), and a
    vocab-sharded probability row implies the lowering gathers (or
    psum-reduces the cumulative mass of) the full distribution — the
    Megatron vocab-parallel sampling collective. Seed/Pos carry no
    layout."""
    probs = _in(op, "Probs")
    sp = spec_of(probs) if probs else REPLICATED_SPEC
    if sp.is_top:
        return _all_outs(op, TOP_SPEC)
    events = []
    shape = shape_of(probs) if probs else None
    if shape is not None:
        a = sp.axis_of(len(shape) - 1)
        if a is not None:
            events.append(CollectiveEvent(
                "allgather", (a,),
                _outs(op)[0] if _outs(op) else None,
                f"categorical draw over the {a}-sharded vocab dim: "
                f"the lowering materializes the full distribution "
                f"(or psums its cumulative mass) across {a!r}"))
    return _all_outs(op, REPLICATED_SPEC, events)


@_family("spec_accept", ("spec_accept",))
def rule_spec_accept(op, spec_of, shape_of, mesh):
    """Draft-and-verify acceptance (ops/spec_ops.py): per-lane
    scalars/short rows out — REPLICATED — computed from per-token
    probability lookups; a vocab-sharded draft/target distribution
    implies a cross-shard gather of the looked-up p/q columns (and of
    the residual distribution for the correction draw)."""
    events = []
    axes = set()
    for slot in ("DraftProbs", "TargetProbs"):
        name = _in(op, slot)
        if name is None:
            continue
        s = spec_of(name)
        if s.is_top:
            return _all_outs(op, TOP_SPEC)
        shape = shape_of(name)
        if shape is not None:
            a = s.axis_of(len(shape) - 1)
            if a is not None:
                axes.add(a)
    if axes:
        events.append(CollectiveEvent(
            "allgather", tuple(sorted(axes)),
            _outs(op)[0] if _outs(op) else None,
            f"speculative acceptance over vocab dims sharded on "
            f"{sorted(axes)}: the p/q token lookups and the residual "
            f"correction distribution materialize across those axes"))
    return _all_outs(op, REPLICATED_SPEC, events)


# ---------------------------------------------------------------------------
# shape-like producers: mint fresh replicated values even when their
# reference input is sharded (they only read its metadata)
# ---------------------------------------------------------------------------
@_family("shape_like", ("fill_constant_batch_size_like", "shape",
                        "range", "fill_constant", "uniform_random",
                        "gaussian_random"))
def rule_shape_like(op, spec_of, shape_of, mesh):
    return _all_outs(op, REPLICATED_SPEC)


# ---------------------------------------------------------------------------
# literal collectives: the result is replicated over the collective
# axis by construction (the order proof for these sites is PTA130's)
# ---------------------------------------------------------------------------
@_family("collective", ("allreduce",))
def rule_collective(op, spec_of, shape_of, mesh):
    return _all_outs(op, REPLICATED_SPEC)
