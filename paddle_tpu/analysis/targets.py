"""Lint targets: every program the repo ships, built for analysis.

One place (shared by ``python -m paddle_tpu.analysis`` and the tier-1
gate test tests/test_analysis_gate.py) that knows how to BUILD each
models/ and benchmark/ program so the checker suite can lint it. Model
builds use small dims — the IR structure (op types, sub-blocks,
companions, param naming) is what the checkers read, and it is
invariant to width — so the whole zoo builds in well under a minute on
CPU. Benchmark programs go through benchmark/fluid_benchmark.py's own
adapters (its default arg shapes) so the exact programs the harness
times are the programs that get linted.

Each target yields ``LintTarget(name, programs, pairs)`` where
`programs` maps a label -> Program (main + startup builds) and `pairs`
lists (label_a, label_b) program pairs for the pairwise sweep named by
``pair_check``: "shared_params" (the default — builds that SHARE
weights by name through one scope, check_shared_params/PTA051) or
"cross_model" (co-resident but UNRELATED serving-runtime models,
check_cross_model_collision/PTA100, where any name overlap is the
defect). Targets that build DecodeStepBundles also carry them in
``bundles`` (label -> bundle) so the whole-bundle contract sweep
(checkers.check_bundle / PTA150) lints each bundle AS A UNIT — the
per-program sweep cannot see cross-specialization disagreements.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Tuple

__all__ = ["LintTarget", "iter_lint_targets", "MODEL_BUILDERS"]


@dataclass
class LintTarget:
    name: str
    programs: Dict[str, object]              # label -> Program
    pairs: List[Tuple[str, str]] = field(default_factory=list)
    pair_check: str = "shared_params"        # or "cross_model"
    bundles: Dict[str, object] = field(default_factory=dict)


def _mnist():
    from ..models import mnist

    main, startup, *_ = mnist.build_program(use_conv=True)
    return {"main": main, "startup": startup}, []


def _resnet():
    from ..models import resnet

    main, startup, _ = resnet.build_program(
        depth=50, class_dim=10, image_shape=(3, 32, 32))
    return {"main": main, "startup": startup}, []


def _vgg():
    from ..models import vgg

    main, startup, _ = vgg.build_program(class_dim=10,
                                         image_shape=(3, 32, 32))
    return {"main": main, "startup": startup}, []


def _se_resnext():
    from ..models import se_resnext

    main, startup, _ = se_resnext.build_program(
        class_dim=10, image_shape=(3, 64, 64))
    return {"main": main, "startup": startup}, []


def _stacked_dynamic_lstm():
    from ..models import stacked_dynamic_lstm

    main, startup, *_ = stacked_dynamic_lstm.build_program(
        dict_dim=1000, emb_dim=64, hid_dim=64, stacked_num=2)
    return {"main": main, "startup": startup}, []


def _machine_translation():
    from ..models import machine_translation as mt

    kw = dict(src_dict_dim=1000, tgt_dict_dim=1000, embedding_dim=32,
              encoder_size=32, decoder_size=32)
    main, startup, _ = mt.build_program(**kw)
    dec = mt.build_decode_program(src_len=8, max_len=8, **kw)
    return ({"main": main, "startup": startup, "decode": dec[0],
             "decode_startup": dec[1]},
            [("main", "decode")])


def _transformer():
    from ..models import transformer as tr
    from ..models.decode_engine import (CacheConfig, DraftConfig,
                                        SamplingConfig)

    kw = dict(seq_len=16, d_model=64, n_heads=4, n_layers=2,
              d_inner=128, vocab=1000)
    main, startup, _ = tr.build_program(dropout_rate=0.1, **kw)
    dkw = dict(seq_len=8, max_out_len=8, d_model=64, n_heads=4,
               n_layers=2, d_inner=128, vocab=1000)
    greedy = tr.build_greedy_decode_program(**dkw)
    incr = tr.build_incremental_decode_program(**dkw)
    beam = tr.build_beam_decode_program(**dkw)
    bundle = tr.build_decode_step_program(n_slots=4, **dkw)
    big = max(bundle.prefills)
    # paged decode-engine layout (block pool + prefix entries): the
    # PTA110 shared-pool sweep and the rest of the suite cover every
    # program flavor the paged server dispatches
    paged = tr.build_decode_step_program(
        n_slots=4, state_prefix="@cbp/",
        cache=CacheConfig(layout="paged", block_size=4, n_blocks=8,
                          n_prompt_entries=3), **dkw)
    pbig = max(paged.prefills)
    # speculative draft-and-verify (r14): the draft prefill/propose,
    # target verify and fused serve programs join the strict zoo —
    # dense AND paged (PTA110 covers the multi-position verify
    # scatter, PTA120 the advance bound), plus a sampled-lane step
    draft = DraftConfig(d_model=32, n_heads=2, n_layers=1,
                        d_inner=64, k=2, k_options=(0, 2))
    # ONE admission bucket per spec-flavor bundle: program structure
    # is bucket-invariant, and the spec serve programs are the
    # biggest builds in the zoo — the gate must stay fast (tier-1).
    # The k-ladder (r19) adds the ("k", 0, base) adaptive variants —
    # the draft-keepalive + plain-body composition — to the sweep.
    spec = tr.build_decode_step_program(
        n_slots=4, state_prefix="@cbs/", draft=draft,
        admit_buckets=[2], **dkw)
    sbig = max(spec.prefills)
    # model-free drafting (r19): the n-gram/prompt-copy propose body
    # (shift-matrix suffix matcher, one-hot dprobs) + its adaptive
    # k=0 rung join the strict zoo
    ngram = tr.build_decode_step_program(
        n_slots=4, state_prefix="@cbn/", admit_buckets=[2],
        draft=DraftConfig(k=2, kind="ngram", ngram=2,
                          k_options=(0, 2)), **dkw)
    pspec = tr.build_decode_step_program(
        n_slots=4, state_prefix="@cbps/", draft=draft,
        admit_buckets=[2],
        cache=CacheConfig(layout="paged", block_size=4, n_blocks=8,
                          n_prompt_entries=3), **dkw)
    psbig = max(pspec.prefills)
    sampled = tr.build_decode_step_program(
        n_slots=4, state_prefix="@cbt/", admit_buckets=[2],
        sampling=SamplingConfig(temperature=0.8, top_k=8,
                                top_p=0.95), **dkw)
    # chunked prefill (ISSUE 17): the ("chunked", p) phase programs
    # join the strict zoo — the embed scatter (0), a kv staging
    # phase (1), an attention phase (2) and the cross-KV install
    # (2L+1) cover every distinct phase-body shape; the bundle
    # contract sweep (PTA150) checks the full set
    chunked = tr.build_decode_step_program(
        n_slots=4, state_prefix="@cbc/", admit_buckets=[2],
        cache=CacheConfig(layout="paged", block_size=4, n_blocks=8,
                          n_prompt_entries=3, chunk_tokens=4), **dkw)
    ckph = len(chunked.chunk_phase_keys) - 1
    # deliberately-misconfigured capacity wedge (PTA200): 5 distinct
    # never-closing session prompts against 3 pinnable prompt entries
    # is the session-pinning admission deadlock the protomodel proves
    # (protomodel.session_protocol) — the zoo keeps it as a COUNTED
    # suppressed witness so the checker's positive case is regression-
    # gated without turning the strict gate red
    wedge = copy.copy(paged)
    wedge.workload = {"distinct_session_prompts": 5,
                      "sessions_close": False}
    wedge._pta_suppress = (
        ("PTA200", "deliberate witness: session-pinning deadlock "
                   "(5 pinned prompts > 3 entries) kept as the "
                   "PTA200 regression wedge"),)
    return ({"main": main, "startup": startup, "greedy": greedy[0],
             "incremental": incr[0], "beam": beam[0],
             "cb_prefill": bundle.prefill,
             f"cb_prefill{big}": bundle.prefills[big],
             "cb_step": bundle.step,
             "cb_serve0": bundle.serves[0],
             f"cb_serve{big}": bundle.serves[big],
             "pg_prefill": paged.prefill,
             f"pg_hit_prefill{pbig}": paged.hit_prefills[pbig],
             "pg_step": paged.step,
             "pg_serve0": paged.serves[0],
             f"pg_serve_miss{pbig}": paged.serves[("miss", pbig)],
             f"pg_serve_hit{pbig}": paged.serves[("hit", pbig)],
             f"pg_serve_radix{pbig}": paged.serves[("radix", pbig)],
             "pg_cow": paged.cow,
             "pg_probe": paged.probe,
             "sp_prefill": spec.prefill,
             "sp_step": spec.step,
             "sp_serve0": spec.serves[0],
             f"sp_serve{sbig}": spec.serves[sbig],
             f"sp_serve_k0_{sbig}": spec.serves[("k", 0, sbig)],
             "ng_step": ngram.step,
             f"ng_serve{sbig}": ngram.serves[sbig],
             f"ng_serve_k0_{sbig}": ngram.serves[("k", 0, sbig)],
             "sps_step": pspec.step,
             f"sps_serve_miss{psbig}": pspec.serves[("miss", psbig)],
             f"sps_serve_hit{psbig}": pspec.serves[("hit", psbig)],
             "smp_step": sampled.step,
             "smp_serve0": sampled.serves[0],
             "ck_chunk_embed": chunked.serves[("chunked", 0)],
             "ck_chunk_kv": chunked.serves[("chunked", 1)],
             "ck_chunk_attn": chunked.serves[("chunked", 2)],
             f"ck_chunk_cross{ckph}":
                 chunked.serves[("chunked", ckph)]},
            [("main", "greedy"), ("main", "incremental"),
             ("main", "beam"), ("main", "cb_prefill"),
             ("main", f"cb_prefill{big}"), ("main", "cb_step"),
             ("main", "cb_serve0"), ("main", f"cb_serve{big}"),
             ("main", "pg_prefill"), ("main", "pg_step"),
             ("main", f"pg_serve_miss{pbig}"),
             ("main", f"pg_serve_hit{pbig}"),
             ("main", f"pg_serve_radix{pbig}"),
             ("main", "pg_cow"), ("main", "pg_probe"),
             ("main", "sp_step"), ("main", f"sp_serve{sbig}"),
             ("main", f"sp_serve_k0_{sbig}"),
             ("main", "ng_step"), ("main", f"ng_serve_k0_{sbig}"),
             ("main", f"sps_serve_miss{psbig}"),
             ("main", "smp_step"),
             ("main", "ck_chunk_kv"),
             ("main", f"ck_chunk_cross{ckph}")],
            "shared_params",
            # whole-bundle contract sweep (PTA150): every bundle the
            # repo ships, checked as a unit
            {"cb": bundle, "pg": paged, "sp": spec, "sps": pspec,
             "ng": ngram, "smp": sampled, "ck": chunked,
             "pg_wedge": wedge})


def _moe_transformer():
    from ..models import moe_transformer

    main, startup, _ = moe_transformer.build_program(
        seq_len=16, vocab=1000, d_model=64, n_heads=4, n_layers=2,
        d_inner=128, n_experts=4)
    return {"main": main, "startup": startup}, []


def _ctr():
    from ..models import ctr

    main, startup, *_ = ctr.build_program(dnn_dict_dim=1001,
                                          lr_dict_dim=1001)
    return {"main": main, "startup": startup}, []


def _word2vec():
    from ..models import word2vec

    main, startup, *_ = word2vec.build_program(dict_size=500,
                                               embed_size=16,
                                               hidden_size=32)
    return {"main": main, "startup": startup}, []


def _recommender():
    from ..models import recommender

    main, startup, *_ = recommender.build_program()
    return {"main": main, "startup": startup}, []


def _label_semantic_roles():
    from ..models import label_semantic_roles

    main, startup, *_ = label_semantic_roles.build_program(seq_len=8)
    return {"main": main, "startup": startup}, []


def _sharded_decoder():
    """The tp-sharded decode engine — the REAL sharded serving
    lowerings (models/decode_engine.ShardingConfig), linted as zoo
    targets: the dense fixture bundle's step + serve programs AND a
    paged+speculative tp bundle, so PTA130/131/160/161 prove every
    shipped sharded serve While branch-free of misplaced collectives
    and PTA190/191 keep the sharded pools' ownership proofs. The
    baseline's ``sharding_facts`` section snapshots the propagated
    specs of all of them."""
    from .. import unique_name
    from ..models import sharded_decoder
    from ..models import transformer as tr
    from ..models.decode_engine import (CacheConfig, DraftConfig,
                                        ShardingConfig)

    fx = sharded_decoder.build_tp_sharded_decoder_step()
    b = fx.bundle
    big = max(b.prefills)
    with unique_name.guard():
        # paged + speculative tp bundle: the sharded pools under the
        # ownership prover + the (k+1)-query verify under the
        # sharding prover, in one build (ONE admission bucket — the
        # gate must stay fast, the targets.py spec-bundle discipline)
        ps = tr.build_decode_step_program(
            seq_len=8, max_out_len=8, d_model=32, n_heads=4,
            n_layers=1, d_inner=64, vocab=64, n_slots=4,
            state_prefix="@tpps/", admit_buckets=[2],
            draft=DraftConfig(d_model=16, n_heads=2, n_layers=1,
                              d_inner=32, k=2),
            cache=CacheConfig(layout="paged", block_size=4,
                              n_blocks=8, n_prompt_entries=3),
            sharding=ShardingConfig(tp=2, qkv_interleaved=True))
    pbig = max(ps.prefills)
    return ({"step": fx.program, "startup": fx.startup,
             "serve0": b.serves[0], f"serve{big}": b.serves[big],
             "prefill": b.prefill,
             "ps_step": ps.step,
             "ps_serve0": ps.serves[0],
             f"ps_serve_miss{pbig}": ps.serves[("miss", pbig)],
             f"ps_serve_hit{pbig}": ps.serves[("hit", pbig)],
             f"ps_prefill{pbig}": ps.prefills[pbig]},
            [("step", "serve0"), ("step", f"serve{big}"),
             ("ps_step", f"ps_serve_miss{pbig}")],
            "shared_params",
            {"tp": b, "tpps": ps})


def _serving_runtime():
    """The multi-tenant runtime's model zoo (inference/runtime/zoo.py
    — the exact programs bench.py's `multitenant` config serves).
    Every distinct model pair is also lint-PAIRED so PTA051/PTA100's
    shared-name sweeps cover the co-residency contract (distinct
    per-model prefixes must keep them silent)."""
    from ..inference.runtime import zoo

    programs = {}
    names = []
    for prefix, in_dim, hidden, classes in zoo.DEFAULT_ZOO:
        main, startup, _feeds, _fetches = zoo.build_fc_program(
            prefix, in_dim, hidden, classes)
        programs[prefix] = main
        programs[f"{prefix}_startup"] = startup
        names.append(prefix)
    pairs = [(a, b) for i, a in enumerate(names)
             for b in names[i + 1:]]
    return programs, pairs, "cross_model"


MODEL_BUILDERS: Dict[str, Callable] = {
    "mnist": _mnist,
    "resnet": _resnet,
    "vgg": _vgg,
    "se_resnext": _se_resnext,
    "stacked_dynamic_lstm": _stacked_dynamic_lstm,
    "machine_translation": _machine_translation,
    "transformer": _transformer,
    "moe_transformer": _moe_transformer,
    "ctr": _ctr,
    "word2vec": _word2vec,
    "recommender": _recommender,
    "label_semantic_roles": _label_semantic_roles,
    "serving_runtime": _serving_runtime,
    "sharded_decoder": _sharded_decoder,
}


def match_targets(only: Optional[List[str]]) -> List[str]:
    """Model names selected by the --only SUBSTRING filters (a lint
    iteration loop types `--only transformer`, not the full target
    name): every model whose ``models/<name>`` contains any filter.
    Empty/None selects everything."""
    if not only:
        return list(MODEL_BUILDERS)
    return [name for name in MODEL_BUILDERS
            if any(s in f"models/{name}" for s in only)]


def _benchmark_targets() -> Iterator[LintTarget]:
    """The benchmark harness's own program builds (its default arg
    shapes). Importable only with the repo root on sys.path; callers
    treat ImportError as 'no benchmark package here'."""
    from benchmark.fluid_benchmark import MODELS, parse_args

    for name, adapter in sorted(MODELS.items()):
        args = parse_args(["--model", name, "--batch_size", "4"])
        main, startup, _loss, _feed, _unit = adapter(args)
        yield LintTarget(f"benchmark/{name}",
                         {"main": main, "startup": startup})


def iter_lint_targets(include_benchmark: bool = True,
                      only: List[str] = None) -> Iterator[LintTarget]:
    selected = set(match_targets(only))
    for name, build in MODEL_BUILDERS.items():
        if only and name not in selected:
            continue
        built = build()
        programs, pairs = built[0], built[1]
        pair_check = built[2] if len(built) > 2 else "shared_params"
        bundles = built[3] if len(built) > 3 else {}
        yield LintTarget(f"models/{name}", programs, pairs,
                         pair_check=pair_check, bundles=bundles)
    if include_benchmark and not only:
        try:
            yield from _benchmark_targets()
        except ImportError:
            pass
