"""Graphviz network drawing (parity: reference python/paddle/fluid/
net_drawer.py + graphviz.py — thin wrappers over debugger's dot
emitter)."""
from __future__ import annotations

from .debugger import draw_block_graphviz

__all__ = ["draw_graph", "Graph"]


class Graph:
    """Minimal graphviz builder (reference graphviz.py Graph)."""

    def __init__(self, title="G", rankdir="TB"):
        self.title = title
        self.rankdir = rankdir
        self.nodes = []
        self.edges = []

    def node(self, name, label=None, **attrs):
        self.nodes.append((name, label or name, attrs))
        return name

    def edge(self, src, dst, **attrs):
        self.edges.append((src, dst, attrs))

    def __str__(self):
        lines = [f"digraph {self.title} {{",
                 f"  rankdir={self.rankdir};"]
        for name, label, attrs in self.nodes:
            extra = "".join(f", {k}={v}" for k, v in attrs.items())
            lines.append(f'  {name} [label="{label}"{extra}];')
        for s, d, attrs in self.edges:
            extra = ", ".join(f"{k}={v}" for k, v in attrs.items())
            lines.append(f"  {s} -> {d}"
                         + (f" [{extra}]" if extra else "") + ";")
        lines.append("}")
        return "\n".join(lines)

    def save(self, path):
        with open(path, "w") as f:
            f.write(str(self))
        return path


def draw_graph(startup_program, main_program, path="./network.dot"):
    """reference net_drawer.py draw_graph: dot file of the main
    program's global block."""
    return draw_block_graphviz(main_program.global_block, path=path)
