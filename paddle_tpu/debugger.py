"""Program pretty-printer (parity: reference python/paddle/fluid/
debugger.py draw_block_graphviz / print-style program dumps)."""
from __future__ import annotations

from typing import Optional

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _fmt_var(var):
    shape = "x".join(str(d) for d in (var.shape or ())) or "?"
    dt = var.dtype.value if var.dtype else "?"
    flags = []
    if var.persistable:
        flags.append("persist")
    if var.is_data:
        flags.append("data")
    f = (" [" + ",".join(flags) + "]") if flags else ""
    return f"{var.name}: {dt}[{shape}]{f}"


def pprint_block_codes(block, show_backward=False) -> str:
    lines = [f"// block {block.idx} (parent {block.parent_idx})"]
    for var in block.vars.values():
        lines.append(f"var {_fmt_var(var)}")
    for op in block.ops:
        if not show_backward and op.attr("op_role") == "backward":
            continue
        ins = ", ".join(f"{s}={v}" for s, v in op.inputs.items() if v)
        outs = ", ".join(f"{s}={v}" for s, v in op.outputs.items()
                         if v)
        attrs = {k: v for k, v in op.attrs.items()
                 if not k.startswith("__") and k != "op_role"}
        lines.append(f"{outs} = {op.type}({ins})"
                     + (f"  # {attrs}" if attrs else ""))
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False) -> str:
    return "\n\n".join(pprint_block_codes(b, show_backward)
                       for b in program.blocks)


def draw_block_graphviz(block, highlights=None,
                        path: str = "./temp.dot") -> str:
    """Emit a graphviz dot file of the block's op/var graph (reference
    debugger.py draw_block_graphviz)."""
    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    for i, op in enumerate(block.ops):
        color = ', style=filled, fillcolor="lightblue"' \
            if op.type in highlights else ""
        lines.append(f'  op_{i} [label="{op.type}", shape=box{color}];')
        for name in op.input_arg_names:
            vid = f'var_{name.replace(".", "_").replace("@", "_")}'
            lines.append(f'  {vid} [label="{name}", shape=ellipse];')
            lines.append(f"  {vid} -> op_{i};")
        for name in op.output_arg_names:
            vid = f'var_{name.replace(".", "_").replace("@", "_")}'
            lines.append(f'  {vid} [label="{name}", shape=ellipse];')
            lines.append(f"  op_{i} -> {vid};")
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot
