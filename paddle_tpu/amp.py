"""Automatic mixed precision (bf16) -- TPU-native AMP.

The reference frameworks of this era run fp32 everywhere; on TPU the
idiomatic default is bf16 compute with fp32 master weights: the MXU's
native input format is bf16 and HBM bandwidth halves. This module is the
policy layer; `core.registry.run_op` consults it on every op:

* WHITE ops (matmul/conv/attention/embedding -- the MXU ops): float32
  inputs are cast to bfloat16, so the matmul runs native-bf16 and its
  activations flow onward in bf16.
* BLACK ops (softmax/losses/norm statistics/reductions/optimizer
  updates): bfloat16 inputs are cast up to float32; parameters are
  never stored in bf16, so optimizer ops always update fp32 masters.
* Everything else is elementwise-ish glue: when enabled, mixed
  bf16/fp32 float inputs are harmonized DOWN to bf16 (a bias or
  residual read in bf16 is cheaper than promoting the activation up),
  except for a small KEEP set whose output dtype is user-contracted.

Because the grad ops re-run the forward kernel under jax.vjp
(core/registry.py make_vjp_grad_kernel), casting an op's inputs before
the kernel automatically gives the backward pass the same precision:
cotangents w.r.t. fp32 leaves come back fp32 (the cast's transpose),
i.e. bf16 compute with fp32 gradient hand-off to the optimizer.

There is no GradScaler: bf16 has fp32's exponent range, so loss scaling
(needed for fp16 CUDA AMP) is unnecessary -- a real TPU-vs-GPU design
divergence, not an omission.

Enable per-process via `paddle_tpu.amp.enable()` / the `amp_guard`
context, or the FLAGS_use_bf16 env var.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterable, Optional

import jax.numpy as jnp

# MXU-bound ops: run in bf16.
WHITE_LIST = {
    "mul", "matmul", "fc", "conv2d", "depthwise_conv2d",
    "conv2d_transpose", "conv3d", "conv3d_transpose", "attention",
    "attention_block", "ffn_block",
    "lookup_table", "sequence_conv", "bilinear_tensor_product",
}

# Numerically sensitive ops: run in fp32.
BLACK_LIST = {
    "softmax", "log_softmax",
    "cross_entropy", "sigmoid_cross_entropy_with_logits",
    "layer_norm", "batch_norm", "group_norm", "instance_norm",
    "data_norm", "l2_normalize", "norm", "lrn",
    "mean", "reduce_mean", "reduce_sum", "reduce_prod", "sum",
    "exp", "log", "pow", "square", "rsqrt", "sqrt",
    "softmax_with_cross_entropy_smooth",
    # optimizer ops always touch fp32 master params
    "sgd", "momentum", "lars_momentum", "adam", "adamax", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "dgc_momentum",
    "clip_by_norm", "squared_l2_norm",
    # accumulation / metric ops
    "accuracy", "auc", "increment",
}

# Ops whose output dtype is part of their user contract: no harmonize.
KEEP_LIST = {"cast", "fill_constant", "assign", "one_hot", "range",
             "uniform_random", "gaussian_random", "eye",
             "fill_zeros_like", "fill_constant_batch_size_like",
             "share_data", "print", "is_empty", "shape",
             # manages its own precision: bf16 [N,V] logits stay put,
             # reductions accumulate fp32 in-register (nn_ops.py swce)
             "softmax_with_cross_entropy"}

_enabled = [os.environ.get("FLAGS_use_bf16", "") in
            ("1", "true", "True")]


def enabled() -> bool:
    return _enabled[0]


def enable(on: bool = True) -> None:
    _enabled[0] = bool(on)


def state_token() -> bool:
    """Part of the Executor's compile-cache key: a program compiled with
    AMP on is a different XLA program than one compiled with it off."""
    return _enabled[0]


@contextlib.contextmanager
def amp_guard(enable_flag: bool = True,
              custom_white_list: Optional[Iterable[str]] = None,
              custom_black_list: Optional[Iterable[str]] = None):
    """Context manager enabling bf16 AMP for programs compiled inside."""
    added_w = set(custom_white_list or ()) - WHITE_LIST
    added_b = set(custom_black_list or ()) - BLACK_LIST
    prev = _enabled[0]
    WHITE_LIST.update(added_w)
    BLACK_LIST.update(added_b)
    _enabled[0] = bool(enable_flag)
    try:
        yield
    finally:
        _enabled[0] = prev
        WHITE_LIST.difference_update(added_w)
        BLACK_LIST.difference_update(added_b)


def _is_f32(x) -> bool:
    return getattr(x, "dtype", None) == jnp.float32


def _is_bf16(x) -> bool:
    return getattr(x, "dtype", None) == jnp.bfloat16


def cast_op_inputs(op_type: str, inputs: dict) -> dict:
    """Apply the AMP policy to a resolved {slot: [values]} input dict.

    Called by run_op for every op when AMP is enabled. Grad ops follow
    their forward op's color (mul_grad is white like mul), so the
    recomputed forward inside the vjp sees identical dtypes.
    """
    base = op_type[:-5] if op_type.endswith("_grad") else op_type
    if base in WHITE_LIST:
        want, pred = jnp.bfloat16, _is_f32
    elif base in BLACK_LIST:
        want, pred = jnp.float32, _is_bf16
    elif base in KEEP_LIST:
        return inputs
    else:
        # harmonize: if any float input is bf16, bring fp32 ones down
        if not any(_is_bf16(v) for vals in inputs.values()
                   for v in vals if v is not None):
            return inputs
        want, pred = jnp.bfloat16, _is_f32
    out = {}
    for slot, vals in inputs.items():
        out[slot] = [v.astype(want) if v is not None and pred(v) else v
                     for v in vals]
    return out
