"""Weight-decay regularizers (reference python/paddle/fluid/regularizer.py).

append_regularization_ops adds grad += coeff * f(param) ops before the
optimizer ops, exactly the reference pipeline; XLA fuses the decay into
the optimizer update.
"""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer",
           "L2DecayRegularizer", "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op("scale", {"X": param}, {"Out": decay},
                        {"scale": self._coeff, "op_role": "backward"})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op("sign", {"X": param}, {"Out": sign},
                        {"op_role": "backward"})
        block.append_op("scale", {"X": sign}, {"Out": decay},
                        {"scale": self._coeff, "op_role": "backward"})
        return decay


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(parameters_and_grads,
                              regularization=None):
    """reference regularizer.py append_regularization_ops: per-param
    regularizer overrides the global one."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        regularization_term = None
        reg = getattr(param, "regularizer", None) or regularization
        if reg is not None:
            block = grad.block
            regularization_term = reg(param, grad, block)
        if regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        new_grad = block.create_var(
            name=grad.name + "@REGULARIZED",
            shape=grad.shape, dtype=grad.dtype)
        block.append_op("sum", {"X": [grad, regularization_term]},
                        {"Out": new_grad}, {"op_role": "backward"})
        params_and_grads.append((param, new_grad))
    return params_and_grads
