"""Evaluator classes (parity: reference python/paddle/fluid/
evaluator.py — graph-building accumulators reset between passes;
largely superseded by metrics.py, kept for surface parity)."""
from __future__ import annotations

import numpy as np

from . import layers
from .core.program import default_main_program, default_startup_program

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance",
           "DetectionMAP"]


class Evaluator:
    """Base: owns accumulator state vars; reset() zeroes them
    (reference evaluator.py Evaluator)."""

    def __init__(self, name=None, **kwargs):
        self.states = []
        self.metrics = []
        self.helper_name = name or self.__class__.__name__

    def reset(self, executor, reset_program=None):
        from .core.scope import global_scope

        for var in self.states:
            val = global_scope()._get(var.name)
            if val is not None:
                global_scope()._set(var.name,
                                    np.zeros_like(np.asarray(val)))

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _create_state(self, suffix, dtype, shape):
        from . import unique_name
        from .core.program import default_startup_program

        block = default_main_program().global_block
        name = unique_name.generate(
            f"{self.helper_name}_{suffix}")
        var = block.create_var(name=name, shape=shape, dtype=dtype,
                               persistable=True)
        sblock = default_startup_program().global_block
        sblock.create_var(name=name, shape=shape, dtype=dtype,
                          persistable=True)
        sblock.append_op("fill_constant", {}, {"Out": [name]},
                         {"shape": list(shape), "dtype": dtype,
                          "value": 0.0})
        self.states.append(var)
        return var


class ChunkEvaluator(Evaluator):
    """Accumulated chunk F1 (reference evaluator.py ChunkEvaluator)."""

    def __init__(self, input, label, chunk_scheme,
                 num_chunk_types, excluded_chunk_types=None):
        super().__init__()
        num_infer, num_label, num_correct = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)[3:]
        self.num_infer_chunks = self._create_state(
            "num_infer", "int64", [1])
        self.num_label_chunks = self._create_state(
            "num_label", "int64", [1])
        self.num_correct_chunks = self._create_state(
            "num_correct", "int64", [1])
        block = default_main_program().global_block
        for acc, cur in ((self.num_infer_chunks, num_infer),
                         (self.num_label_chunks, num_label),
                         (self.num_correct_chunks, num_correct)):
            block.append_op("elementwise_add",
                            {"X": [acc.name], "Y": [cur.name]},
                            {"Out": [acc.name]}, {})
        self.metrics = [self.num_infer_chunks, self.num_label_chunks,
                        self.num_correct_chunks]

    def eval(self, executor, eval_program=None):
        from .core.scope import global_scope

        ni = float(np.asarray(
            global_scope()._get(self.num_infer_chunks.name)))
        nl = float(np.asarray(
            global_scope()._get(self.num_label_chunks.name)))
        nc = float(np.asarray(
            global_scope()._get(self.num_correct_chunks.name)))
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return precision, recall, f1


class EditDistance(Evaluator):
    """Accumulated average edit distance (reference evaluator.py)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__()
        dist, seq_num = layers.edit_distance(
            input, label, ignored_tokens=ignored_tokens)
        self.total_distance = self._create_state("total_dist",
                                                 "float32", [1])
        self.seq_num = self._create_state("seq_num", "int64", [1])
        block = default_main_program().global_block
        summed = layers.reduce_sum(dist)
        block.append_op("elementwise_add",
                        {"X": [self.total_distance.name],
                         "Y": [summed.name]},
                        {"Out": [self.total_distance.name]}, {})
        block.append_op("elementwise_add",
                        {"X": [self.seq_num.name],
                         "Y": [seq_num.name]},
                        {"Out": [self.seq_num.name]}, {})

    def eval(self, executor, eval_program=None):
        from .core.scope import global_scope

        total = float(np.asarray(
            global_scope()._get(self.total_distance.name)))
        n = float(np.asarray(global_scope()._get(self.seq_num.name)))
        return total / n if n else 0.0


class _EvaluatorDetectionMAP:
    """reference evaluator.py DetectionMAP (the pre-metrics API):
    wraps metrics.DetectionMAP, keeping the Evaluator-style
    reset(executor, reset_program) signature legacy scripts call."""

    def __init__(self, *args, **kwargs):
        from .metrics import DetectionMAP as _M

        self._m = _M(*args, **kwargs)

    def get_map_var(self):
        return self._m.get_map_var()

    def update(self, *args, **kwargs):
        return self._m.update(*args, **kwargs)

    def eval(self, executor=None, eval_program=None):
        return self._m.eval()

    def reset(self, executor=None, reset_program=None):
        return self._m.reset()


DetectionMAP = _EvaluatorDetectionMAP
