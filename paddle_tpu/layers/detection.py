"""Detection layers (reference python/paddle/fluid/layers/detection.py +
operators/detection/ -- prior_box, multiclass_nms, yolov3_loss, etc.).
Kernels in ops/detection_ops.py.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "yolo_box",
           "yolov3_loss", "multiclass_nms", "density_prior_box",
           "anchor_generator", "bipartite_match", "target_assign",
           "ssd_loss", "detection_output", "polygon_box_transform",
           "rpn_target_assign", "generate_proposals",
           "generate_proposal_labels", "box_clip"]


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=[1.0], variance=[0.1, 0.1, 0.2, 0.2],
              flip=False, clip=False, steps=[0.0, 0.0], offset=0.5,
              name=None, min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", input=input, name=name)
    box = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "prior_box", {"Input": input, "Image": image},
        {"Boxes": box, "Variances": var},
        {"min_sizes": list(min_sizes),
         "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios),
         "variances": list(variance), "flip": flip, "clip": clip,
         "step_w": steps[0], "step_h": steps[1], "offset": offset,
         "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return box, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("iou_similarity", {"X": x, "Y": y}, {"Out": out},
                     {})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper("box_coder", input=prior_box, name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op(
        "box_coder",
        {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
         "TargetBox": target_box},
        {"OutputBox": out},
        {"code_type": code_type, "box_normalized": box_normalized,
         "axis": axis})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("box_clip", {"Input": input, "ImInfo": im_info},
                     {"Output": out}, {})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, name=None):
    helper = LayerHelper("yolo_box", input=x, name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype, True)
    scores = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("yolo_box", {"X": x, "ImgSize": img_size},
                     {"Boxes": boxes, "Scores": scores},
                     {"anchors": list(anchors), "class_num": class_num,
                      "conf_thresh": conf_thresh,
                      "downsample_ratio": downsample_ratio})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    helper = LayerHelper("yolov3_loss", input=x, name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "yolov3_loss",
        {"X": x, "GTBox": gt_box, "GTLabel": gt_label},
        {"Loss": loss},
        {"anchors": list(anchors), "anchor_mask": list(anchor_mask),
         "class_num": class_num, "ignore_thresh": ignore_thresh,
         "downsample_ratio": downsample_ratio,
         "use_label_smooth": use_label_smooth})
    return loss


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", input=bboxes, name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype, True)
    helper.append_op(
        "multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
        {"Out": out},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "normalized": normalized, "nms_eta": nms_eta,
         "background_label": background_label})
    return out


def density_prior_box(*args, **kwargs):
    raise NotImplementedError("density_prior_box: planned (ops/detection)")


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None,
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", input=input, name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype,
                                                        True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "anchor_generator", {"Input": input},
        {"Anchors": anchors, "Variances": var},
        {"anchor_sizes": list(anchor_sizes),
         "aspect_ratios": list(aspect_ratios),
         "variances": list(variance), "stride": list(stride),
         "offset": offset})
    return anchors, var


def bipartite_match(*args, **kwargs):
    raise NotImplementedError(
        "bipartite_match: greedy host-side matching; planned")


def target_assign(*args, **kwargs):
    raise NotImplementedError("target_assign: planned")


def ssd_loss(*args, **kwargs):
    raise NotImplementedError("ssd_loss: planned (needs bipartite_match)")


def detection_output(*args, **kwargs):
    raise NotImplementedError("detection_output: planned")


def polygon_box_transform(*args, **kwargs):
    raise NotImplementedError("polygon_box_transform: planned")


def rpn_target_assign(*args, **kwargs):
    raise NotImplementedError("rpn_target_assign: planned")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError("generate_proposals: planned")


def generate_proposal_labels(*args, **kwargs):
    raise NotImplementedError("generate_proposal_labels: planned")
