"""Detection layers (reference python/paddle/fluid/layers/detection.py +
operators/detection/ -- prior_box, multiclass_nms, yolov3_loss, etc.).
Kernels in ops/detection_ops.py.
"""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper

__all__ = ["detection_map",
           "prior_box", "box_coder", "iou_similarity", "yolo_box",
           "yolov3_loss", "multiclass_nms", "density_prior_box",
           "anchor_generator", "bipartite_match", "target_assign",
           "ssd_loss", "detection_output", "polygon_box_transform",
           "rpn_target_assign", "generate_proposals",
           "generate_proposal_labels", "box_clip"]


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=[1.0], variance=[0.1, 0.1, 0.2, 0.2],
              flip=False, clip=False, steps=[0.0, 0.0], offset=0.5,
              name=None, min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", input=input, name=name)
    box = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "prior_box", {"Input": input, "Image": image},
        {"Boxes": box, "Variances": var},
        {"min_sizes": list(min_sizes),
         "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios),
         "variances": list(variance), "flip": flip, "clip": clip,
         "step_w": steps[0], "step_h": steps[1], "offset": offset,
         "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return box, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("iou_similarity", {"X": x, "Y": y}, {"Out": out},
                     {})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper("box_coder", input=prior_box, name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op(
        "box_coder",
        {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
         "TargetBox": target_box},
        {"OutputBox": out},
        {"code_type": code_type, "box_normalized": box_normalized,
         "axis": axis})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("box_clip", {"Input": input, "ImInfo": im_info},
                     {"Output": out}, {})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, name=None):
    helper = LayerHelper("yolo_box", input=x, name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype, True)
    scores = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("yolo_box", {"X": x, "ImgSize": img_size},
                     {"Boxes": boxes, "Scores": scores},
                     {"anchors": list(anchors), "class_num": class_num,
                      "conf_thresh": conf_thresh,
                      "downsample_ratio": downsample_ratio})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    helper = LayerHelper("yolov3_loss", input=x, name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "yolov3_loss",
        {"X": x, "GTBox": gt_box, "GTLabel": gt_label},
        {"Loss": loss},
        {"anchors": list(anchors), "anchor_mask": list(anchor_mask),
         "class_num": class_num, "ignore_thresh": ignore_thresh,
         "downsample_ratio": downsample_ratio,
         "use_label_smooth": use_label_smooth})
    return loss


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", input=bboxes, name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype, True)
    helper.append_op(
        "multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
        {"Out": out},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "normalized": normalized, "nms_eta": nms_eta,
         "background_label": background_label})
    return out


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", input=input, name=name)
    box = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "density_prior_box", {"Input": input, "Image": image},
        {"Boxes": box, "Variances": var},
        {"densities": list(densities or []),
         "fixed_sizes": list(fixed_sizes or []),
         "fixed_ratios": list(fixed_ratios or [1.0]),
         "variances": list(variance), "clip": clip,
         "step_w": steps[0], "step_h": steps[1], "offset": offset})
    if flatten_to_2d:
        from .nn import reshape

        box = reshape(box, shape=[-1, 4])
        var = reshape(var, shape=[-1, 4])
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None,
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", input=input, name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype,
                                                        True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "anchor_generator", {"Input": input},
        {"Anchors": anchors, "Variances": var},
        {"anchor_sizes": list(anchor_sizes),
         "aspect_ratios": list(aspect_ratios),
         "variances": list(variance), "stride": list(stride),
         "offset": offset})
    return anchors, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", input=dist_matrix, name=name)
    match_indices = helper.create_variable_for_type_inference("int32",
                                                              True)
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype, True)
    helper.append_op(
        "bipartite_match", {"DistMat": dist_matrix},
        {"ColToRowMatchIndices": match_indices,
         "ColToRowMatchDist": match_distance},
        {"match_type": match_type or "bipartite",
         "dist_threshold": (0.5 if dist_threshold is None
                            else dist_threshold)})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype, True)
    out_weight = helper.create_variable_for_type_inference("float32",
                                                           True)
    ins = {"X": input, "MatchIndices": matched_indices}
    if negative_indices is not None:
        ins["NegIndices"] = negative_indices
    helper.append_op(
        "target_assign", ins,
        {"Out": out, "OutWeight": out_weight},
        {"mismatch_value": mismatch_value or 0})
    return out, out_weight


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0,
             neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None, name=None):
    """Fused SSD multibox loss (the reference composes ~10 ops in
    layers/detection.py ssd_loss; here one fused XLA kernel)."""
    helper = LayerHelper("ssd_loss", input=location, name=name)
    loss = helper.create_variable_for_type_inference(location.dtype)
    ins = {"Location": location, "Confidence": confidence,
           "GTBox": gt_box, "GTLabel": gt_label, "PriorBox": prior_box}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = prior_box_var
    helper.append_op(
        "ssd_loss", ins, {"Loss": loss},
        {"background_label": background_label,
         "overlap_threshold": overlap_threshold,
         "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
         "loc_loss_weight": loc_loss_weight,
         "conf_loss_weight": conf_loss_weight,
         "match_type": match_type, "mining_type": mining_type,
         "normalize": normalize, "sample_size": sample_size or 0})
    return loss


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3,
                     nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, nms_eta=1.0, name=None):
    """SSD output: decode loc at priors then class-wise NMS (reference
    layers/detection.py detection_output = box_coder + multiclass_nms)."""
    helper = LayerHelper("detection_output", input=loc, name=name)
    decoded = helper.create_variable_for_type_inference(loc.dtype)
    helper.append_op(
        "box_coder",
        {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
         "TargetBox": loc},
        {"OutputBox": decoded},
        {"code_type": "decode_center_size"})
    from .nn import transpose

    scores_t = transpose(scores, perm=[0, 2, 1])  # [B, C, M]
    return multiclass_nms(
        decoded, scores_t, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, background_label=background_label)


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("polygon_box_transform", {"Input": input},
                     {"Output": out}, {})
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """Per-anchor labels [-1/0/1] + encoded bbox targets, fixed shape
    (the reference emits gathered index lists; see ops/detection_ops.py
    rpn_target_assign for the XLA-native padded encoding)."""
    helper = LayerHelper("rpn_target_assign", input=anchor_box)
    labels = helper.create_variable_for_type_inference("int32", True)
    targets = helper.create_variable_for_type_inference(
        anchor_box.dtype, True)
    inside_w = helper.create_variable_for_type_inference(
        anchor_box.dtype, True)
    helper.append_op(
        "rpn_target_assign",
        {"Anchor": anchor_box, "GtBoxes": gt_boxes},
        {"LocationIndex": labels, "ScoreIndex": labels,
         "TargetLabel": labels, "TargetBBox": targets,
         "BBoxInsideWeight": inside_w},
        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
         "rpn_fg_fraction": rpn_fg_fraction,
         "rpn_positive_overlap": rpn_positive_overlap,
         "rpn_negative_overlap": rpn_negative_overlap,
         "use_random": use_random})
    return labels, targets, inside_w


def generate_proposals(scores, bbox_deltas, im_info, anchors,
                       variances=None, pre_nms_top_n=6000,
                       post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, name=None):
    helper = LayerHelper("generate_proposals", input=scores, name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype, True)
    probs = helper.create_variable_for_type_inference(scores.dtype,
                                                      True)
    helper.append_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": bbox_deltas,
         "ImInfo": im_info, "Anchors": anchors},
        {"RpnRois": rois, "RpnRoiProbs": probs},
        {"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
         "nms_thresh": nms_thresh, "min_size": min_size})
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None, use_random=True):
    """Match rois to gt, label fg/bg, subsample, encode bbox targets
    (reference generate_proposal_labels_op.cc; see ops/detection_ops.py
    for the fixed-shape encoding; is_crowd exclusion is not modeled)."""
    import warnings

    if is_crowd is not None:
        warnings.warn("generate_proposal_labels: is_crowd exclusion is "
                      "not modeled; crowd boxes are treated as regular "
                      "gt", stacklevel=2)
    helper = LayerHelper("generate_proposal_labels", input=rpn_rois)
    labels = helper.create_variable_for_type_inference("int32", True)
    targets = helper.create_variable_for_type_inference(
        rpn_rois.dtype, True)
    inside_w = helper.create_variable_for_type_inference(
        rpn_rois.dtype, True)
    outside_w = helper.create_variable_for_type_inference(
        rpn_rois.dtype, True)
    rois_out = helper.create_variable_for_type_inference(
        rpn_rois.dtype, True)
    helper.append_op(
        "generate_proposal_labels",
        {"RpnRois": rpn_rois, "GtClasses": gt_classes,
         "GtBoxes": gt_boxes},
        {"Rois": rois_out, "LabelsInt32": labels,
         "BboxTargets": targets, "BboxInsideWeights": inside_w,
         "BboxOutsideWeights": outside_w},
        {"batch_size_per_im": batch_size_per_im,
         "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
         "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
         "bbox_reg_weights": list(bbox_reg_weights),
         "use_random": use_random})
    return rois_out, labels, targets, inside_w, outside_w


def detection_map(detect_res, label, class_num=None, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral", has_difficult=False):
    """reference layers/detection.py detection_map: mAP over padded
    detections [B,D,6] vs padded gt [B,G,5]. class_num /
    evaluate_difficult / state vars are accepted for API parity; the
    op computes per-batch mAP on host (ops/detection_ops.py) and
    accumulation lives in metrics.DetectionMAP."""
    helper = LayerHelper("detection_map")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "detection_map",
        {"DetectRes": [detect_res.name], "Label": [label.name]},
        {"MAP": [out.name]},
        {"overlap_threshold": overlap_threshold,
         "ap_type": ap_version,
         "background_label": background_label,
         "evaluate_difficult": evaluate_difficult,
         "has_difficult": bool(has_difficult)})
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip, name=None):
    """reference layers/detection.py box_decoder_and_assign ->
    detection/box_decoder_and_assign_op.cc."""
    helper = LayerHelper("box_decoder_and_assign", input=prior_box,
                         name=name)
    decoded = helper.create_variable_for_type_inference(
        prior_box.dtype)
    assigned = helper.create_variable_for_type_inference(
        prior_box.dtype)
    helper.append_op(
        "box_decoder_and_assign",
        {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
         "TargetBox": target_box, "BoxScore": box_score},
        {"DecodeBox": decoded, "OutputAssignBox": assigned},
        {"box_clip": box_clip})
    return decoded, assigned


def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale, name=None):
    """reference layers/detection.py distribute_fpn_proposals; the TPU
    fixed-shape contract packs each level's rois to the top with a
    per-level count vector (see ops/detection_ops.py)."""
    helper = LayerHelper("distribute_fpn_proposals", input=fpn_rois,
                         name=name)
    num_level = max_level - min_level + 1
    multi_rois = [helper.create_variable_for_type_inference(
        fpn_rois.dtype) for _ in range(num_level)]
    counts = helper.create_variable_for_type_inference("int32", True)
    restore = helper.create_variable_for_type_inference("int32", True)
    helper.append_op(
        "distribute_fpn_proposals", {"FpnRois": fpn_rois},
        {"MultiFpnRois": multi_rois, "MultiLevelCounts": counts,
         "RestoreIndex": restore},
        {"min_level": min_level, "max_level": max_level,
         "refer_level": refer_level, "refer_scale": refer_scale})
    return multi_rois, restore


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    """reference layers/detection.py roi_perspective_transform ->
    detection/roi_perspective_transform_op.cc (quad rois, 8 coords)."""
    helper = LayerHelper("roi_perspective_transform", input=input,
                         name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "roi_perspective_transform", {"X": input, "ROIs": rois},
        {"Out": out},
        {"transformed_height": transformed_height,
         "transformed_width": transformed_width,
         "spatial_scale": spatial_scale})
    return out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms,
                         rois, labels_int32, num_classes, resolution,
                         gt_boxes=None, poly_len=None, name=None):
    """reference layers/detection.py generate_mask_labels ->
    detection/mask_util.cc + generate_mask_labels_op.cc (Mask R-CNN
    mask targets; polygons rasterized host-side via py-callback).

    Deviation from the reference signature: the fixed-shape kernel
    needs gt_boxes [G,4] and poly_len [G] explicitly (the reference
    recovers boxes from LoD-segmented polygons; the padded design
    carries them as separate inputs) — both are REQUIRED here."""
    if gt_boxes is None or poly_len is None:
        raise ValueError(
            "generate_mask_labels on TPU needs gt_boxes=[G,4] and "
            "poly_len=[G] (the padded-polygon companions; see "
            "ops/detection_ops.py generate_mask_labels)")
    helper = LayerHelper("generate_mask_labels", input=rois, name=name)
    mask_rois = helper.create_variable_for_type_inference(rois.dtype,
                                                          True)
    has_mask = helper.create_variable_for_type_inference("int32", True)
    mask_int32 = helper.create_variable_for_type_inference("int32",
                                                           True)
    ins = {"Rois": rois, "LabelsInt32": labels_int32,
           "GtBoxes": gt_boxes, "GtSegms": gt_segms,
           "PolyLen": poly_len}
    helper.append_op(
        "generate_mask_labels", ins,
        {"MaskRois": mask_rois, "RoiHasMaskInt32": has_mask,
         "MaskInt32": mask_int32},
        {"num_classes": num_classes, "resolution": resolution})
    return mask_rois, has_mask, mask_int32


def multi_box_head(inputs, image, base_size, num_classes,
                   aspect_ratios, min_ratio=None, max_ratio=None,
                   min_sizes=None, max_sizes=None, steps=None,
                   step_w=None, step_h=None, offset=0.5, flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1,
                   name=None, min_max_aspect_ratios_order=False):
    """SSD detection head (reference layers/detection.py
    multi_box_head): per feature map, a prior_box + two convs (loc,
    conf) whose outputs are flattened and concatenated across maps.
    Returns (mbox_locs, mbox_confs, boxes, variances)."""
    from . import nn

    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spread min_ratio..max_ratio
        # over the deeper maps, first map fixed at base_size*0.1
        min_sizes, max_sizes = [], []
        step_r = int(np.floor((max_ratio - min_ratio) /
                              max(n_layer - 2, 1)))
        for ratio in range(min_ratio, max_ratio + 1, step_r):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step_r) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        minsz = min_sizes[i]
        maxsz = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) else \
            [aspect_ratios[i]]
        st = steps[i] if steps else [step_w or 0.0, step_h or 0.0]
        if not isinstance(st, (list, tuple)):
            st = [st, st]
        box, var = prior_box(
            x, image, [minsz] if not isinstance(
                minsz, (list, tuple)) else list(minsz),
            [maxsz] if maxsz and not isinstance(
                maxsz, (list, tuple)) else (list(maxsz or [])),
            ar, flip=flip, clip=clip, steps=list(st), offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        num_priors_per_cell = int(box.shape[2]) if box.shape and \
            len(box.shape) == 4 else None
        # boxes come out [H, W, P, 4] -> flatten to [H*W*P, 4]
        boxes_all.append(nn.reshape(box, shape=[-1, 4]))
        vars_all.append(nn.reshape(var, shape=[-1, 4]))
        num_priors = num_priors_per_cell or 1
        loc = nn.conv2d(x, num_priors * 4, kernel_size, stride=stride,
                        padding=pad)
        conf = nn.conv2d(x, num_priors * num_classes, kernel_size,
                         stride=stride, padding=pad)
        # NCHW -> NHWC -> [N, boxes, 4/classes]
        locs.append(nn.reshape(
            nn.transpose(loc, perm=[0, 2, 3, 1]), shape=[0, -1, 4]))
        confs.append(nn.reshape(
            nn.transpose(conf, perm=[0, 2, 3, 1]),
            shape=[0, -1, num_classes]))
    mbox_locs = nn.concat(locs, axis=1) if len(locs) > 1 else locs[0]
    mbox_confs = nn.concat(confs, axis=1) if len(confs) > 1 else \
        confs[0]
    boxes = nn.concat(boxes_all, axis=0) if len(boxes_all) > 1 else \
        boxes_all[0]
    variances = nn.concat(vars_all, axis=0) if len(vars_all) > 1 else \
        vars_all[0]
    return mbox_locs, mbox_confs, boxes, variances


__all__.extend(["box_decoder_and_assign", "distribute_fpn_proposals",
                "roi_perspective_transform", "generate_mask_labels",
                "multi_box_head"])
