"""NN layers (reference python/paddle/fluid/layers/nn.py -- 177 functions).

Each function builds ops into the default main program via LayerHelper,
mirroring the reference's graph-construction API; execution is deferred to
the XLA-compiling Executor.
"""
from __future__ import annotations

import numpy as np

from ..core.program import Variable
from ..core.types import as_datatype
from ..initializer import ConstantInitializer, NormalInitializer, \
    XavierInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose", "pool2d",
    "adaptive_pool2d", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "dropout", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost",
    "huber_loss", "log_loss", "smooth_l1", "hinge_loss",
    "margin_rank_loss", "bpr_loss", "kldiv_loss",
    "mean", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "reduce_all", "reduce_any",
    "matmul", "mul", "dot", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_pow", "elementwise_mod",
    "elementwise_floordiv",
    "reshape", "squeeze", "unsqueeze", "transpose", "flatten", "concat",
    "split", "stack", "unstack", "expand", "expand_as", "slice",
    "strided_slice", "gather", "gather_nd", "scatter", "pad", "pad2d",
    "crop", "one_hot", "topk", "argsort", "argmax", "argmin", "where",
    "scale", "cast", "clip", "clip_by_norm", "l2_normalize",
    "lrn", "relu", "leaky_relu", "prelu", "maxout", "swish",
    "hard_swish", "hard_sigmoid", "elu", "relu6", "pow", "soft_relu",
    "brelu", "label_smooth", "cos_sim", "dice_loss", "npair_loss",
    "image_resize", "resize_bilinear", "resize_nearest", "grid_sampler",
    "affine_grid", "affine_channel", "shuffle_channel", "pixel_shuffle",
    "roi_pool", "roi_align", "psroi_pool", "row_conv",
    "increment", "zeros_like", "ones_like", "shape", "reverse",
    "uniform_random_batch_size_like", "gaussian_random",
    "sampling_id", "sums", "sum", "lstm", "dynamic_lstm", "dynamic_gru",
    "gru_unit", "lstm_unit", "beam_search", "beam_search_decode",
    "sequence_conv", "sequence_pool", "sequence_softmax",
    "sequence_expand", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_reshape", "sequence_pad",
    "sequence_unpad", "sequence_reverse", "sequence_slice",
    "sequence_enumerate", "sequence_expand_as", "sequence_scatter",
    "edit_distance", "ctc_greedy_decoder", "warpctc", "nce",
    "hsigmoid", "sampled_softmax_with_cross_entropy", "im2sequence",
    "multiplex", "smooth_l1_loss", "spectral_norm", "temporal_shift",
    "pixel_unshuffle", "unfold", "deformable_conv",
]


def _single_out(helper, op_type, inputs, attrs=None, dtype=None,
                out_slot="Out"):
    out = helper.create_variable_for_type_inference(
        dtype or helper.input_dtype() if helper.kwargs.get("input")
        is not None else dtype)
    helper.append_op(op_type, inputs, {out_slot: out}, attrs or {})
    return out


# ---------------------------------------------------------------------------
# dense / conv / norm
# ---------------------------------------------------------------------------
def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected (reference layers/nn.py fc): out = act(X W + b).

    Multiple inputs are summed after their own matmuls, like the reference.
    """
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = helper.param_attr
    if not isinstance(param_attrs, (list, tuple)):
        param_attrs = [param_attrs] * len(inputs)
    mul_results = []
    for x, pattr in zip(inputs, param_attrs):
        in_features = int(np.prod(x.shape[num_flatten_dims:]))
        w = helper.create_parameter(pattr, [in_features, size], x.dtype)
        tmp = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op("mul", {"X": x, "Y": w}, {"Out": tmp},
                         {"x_num_col_dims": num_flatten_dims,
                          "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(
            inputs[0].dtype)
        helper.append_op("sum", {"X": mul_results}, {"Out": pre_bias}, {})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32",
              name=None):
    """reference layers/nn.py embedding -> lookup_table op."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    w = helper.create_parameter(helper.param_attr, size, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lookup_table", {"Ids": input, "W": w}, {"Out": out},
        {"is_sparse": is_sparse, "is_distributed": is_distributed,
         "padding_idx": -1 if padding_idx is None else padding_idx})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None):
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups, fs[0], fs[1]]
    std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, filter_shape, input.dtype,
        default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d", {"Input": input, "Filter": w}, {"Output": out},
        {"strides": _pair(stride), "paddings": _pair(padding),
         "dilations": _pair(dilation), "groups": groups})
    out = _conv_bias(helper, out)
    return helper.append_activation(out)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None):
    helper = LayerHelper("conv3d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    w = helper.create_parameter(
        helper.param_attr, [num_filters, c // groups] + list(fs),
        input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d", {"Input": input, "Filter": w}, {"Output": out},
        {"strides": _triple(stride), "paddings": _triple(padding),
         "dilations": _triple(dilation), "groups": groups})
    out = _conv_bias(helper, out)
    return helper.append_activation(out)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    c = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    w = helper.create_parameter(
        helper.param_attr, [c, num_filters // groups, fs[0], fs[1]],
        input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d_transpose", {"Input": input, "Filter": w},
        {"Output": out},
        {"strides": _pair(stride), "paddings": _pair(padding),
         "dilations": _pair(dilation), "groups": groups})
    out = _conv_bias(helper, out)
    return helper.append_activation(out)


def _conv_bias(helper, out):
    bias_attr = helper.bias_attr
    if bias_attr is False:
        return out
    b = helper.create_parameter(bias_attr, [out.shape[1]], out.dtype,
                                is_bias=True)
    if b is None:
        return out
    new = helper.create_variable_for_type_inference(out.dtype)
    helper.append_op("elementwise_add", {"X": out, "Y": b}, {"Out": new},
                     {"axis": 1})
    return new


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool2d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d", {"X": input}, {"Out": out},
        {"pooling_type": pool_type, "ksize": _pair(pool_size),
         "strides": _pair(pool_stride), "paddings": _pair(pool_padding),
         "global_pooling": global_pooling, "ceil_mode": ceil_mode,
         "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("adaptive_pool2d", {"X": input}, {"Out": out},
                     {"pooling_size": _pair(pool_size),
                      "pooling_type": pool_type})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=
               False, use_global_stats=False):
    """reference layers/nn.py batch_norm; running stats are persistable
    state threaded through the executor (MeanOut/VarianceOut)."""
    helper = LayerHelper("batch_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = input.dtype
    scale = helper.create_parameter(
        helper.param_attr, [c], dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, [c], dtype,
                                   is_bias=True)
    mean = helper.create_global_variable(
        [c], dtype, persistable=True,
        name=moving_mean_name, stop_gradient=True)
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        [c], dtype, persistable=True,
        name=moving_variance_name, stop_gradient=True)
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))
    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "batch_norm",
        {"X": input, "Scale": scale, "Bias": bias, "Mean": mean,
         "Variance": variance},
        {"Y": out, "MeanOut": mean, "VarianceOut": variance,
         "SavedMean": saved_mean, "SavedVariance": saved_var},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout,
         "use_global_stats": use_global_stats})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    dim = int(np.prod(input.shape[begin_norm_axis:]))
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, [dim], dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(helper.bias_attr, [dim], dtype,
                                    is_bias=True)
        if b is not None:
            inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, True)
    var = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("layer_norm", inputs,
                     {"Y": out, "Mean": mean, "Variance": var},
                     {"epsilon": epsilon,
                      "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    inputs = {"X": input}
    if param_attr is not False:
        inputs["Scale"] = helper.create_parameter(
            helper.param_attr, [c], input.dtype,
            default_initializer=ConstantInitializer(1.0))
    if bias_attr is not False:
        inputs["Bias"] = helper.create_parameter(
            helper.bias_attr, [c], input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("group_norm", inputs,
                     {"Y": out, "Mean": mean, "Variance": var},
                     {"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    c = input.shape[1]
    inputs = {"X": input}
    if param_attr is not False:
        inputs["Scale"] = helper.create_parameter(
            helper.param_attr, [c], input.dtype,
            default_initializer=ConstantInitializer(1.0))
    if bias_attr is not False:
        inputs["Bias"] = helper.create_parameter(
            helper.bias_attr, [c], input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    sm = helper.create_variable_for_type_inference(input.dtype, True)
    sv = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("instance_norm", inputs,
                     {"Y": out, "SavedMean": sm, "SavedVariance": sv},
                     {"epsilon": epsilon})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", input=weight, name=name)
    out = helper.create_variable_for_type_inference(weight.dtype)
    h = weight.shape[dim]
    import functools
    w = int(np.prod(weight.shape)) // h
    u = helper.create_parameter(None, [h], weight.dtype,
                                default_initializer=NormalInitializer())
    v = helper.create_parameter(None, [w], weight.dtype,
                                default_initializer=NormalInitializer())
    helper.append_op("spectral_norm",
                     {"Weight": weight, "U": u, "V": v}, {"Out": out},
                     {"dim": dim, "power_iters": power_iters, "eps": eps})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("dropout", {"X": x}, {"Out": out, "Mask": mask},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "seed": seed or 0,
                      "dropout_implementation": dropout_implementation})
    return out


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1,
                               label_smooth_eps=0.0):
    helper = LayerHelper("softmax_with_cross_entropy", input=logits)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    sm = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": label},
                     {"Loss": loss, "Softmax": sm},
                     {"soft_label": soft_label,
                      "ignore_index": ignore_index,
                      "label_smooth_eps": label_smooth_eps})
    if return_softmax:
        return loss, sm
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy", {"X": input, "Label": label},
                     {"Y": out} if False else {"Out": out},
                     {"soft_label": soft_label,
                      "ignore_index": ignore_index})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": x, "Label": label}, {"Out": out},
                     {"ignore_index": ignore_index,
                      "normalize": normalize})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square_error_cost", {"X": input, "Y": label},
                     {"Out": out}, {})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    res = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("huber_loss", {"X": input, "Y": label},
                     {"Out": out, "Residual": res}, {"delta": delta})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_loss", {"Predicted": input, "Labels": label},
                     {"Loss": out}, {"epsilon": epsilon})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype, True)
    ins = {"X": x, "Y": y}
    if inside_weight is not None:
        ins["InsideWeight"] = inside_weight
    if outside_weight is not None:
        ins["OutsideWeight"] = outside_weight
    helper.append_op("smooth_l1_loss", ins,
                     {"Out": out, "Diff": diff},
                     {"sigma": sigma or 1.0})
    return out


smooth_l1_loss = smooth_l1


def hinge_loss(input, label):
    helper = LayerHelper("hinge_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("hinge_loss", {"Logits": input, "Labels": label},
                     {"Loss": out}, {})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", input=left)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype, True)
    helper.append_op("margin_rank_loss",
                     {"Label": label, "X1": left, "X2": right},
                     {"Out": out, "Activated": act}, {"margin": margin})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("bpr_loss", {"X": input, "Label": label},
                     {"Out": out}, {})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kldiv_loss", {"X": x, "Target": target},
                     {"Loss": out}, {"reduction": reduction})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", input=label)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": label}
    if prior_dist is not None:
        ins["PriorDist"] = prior_dist
    helper.append_op("label_smooth", ins, {"Out": out},
                     {"epsilon": epsilon})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim", input=X)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype, True)
    yn = helper.create_variable_for_type_inference(X.dtype, True)
    helper.append_op("cos_sim", {"X": X, "Y": Y},
                     {"Out": out, "XNorm": xn, "YNorm": yn}, {})
    return out


def dice_loss(input, label, epsilon=1e-5):
    helper = LayerHelper("dice_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("dice_loss", {"X": input, "Label": label},
                     {"Out": out}, {"epsilon": epsilon})
    return out


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    helper = LayerHelper("npair_loss", input=anchor)
    out = helper.create_variable_for_type_inference(anchor.dtype)
    helper.append_op("npair_loss",
                     {"Anchor": anchor, "Positive": positive,
                      "Labels": labels},
                     {"Out": out}, {"l2_reg": l2_reg})
    return out


# ---------------------------------------------------------------------------
# generated elementwise / unary / reduce wrappers
# ---------------------------------------------------------------------------
def _make_elementwise(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, input=x, act=act, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out},
                         {"axis": axis})
        return helper.append_activation(out)

    layer.__name__ = op_type
    return layer


elementwise_add = _make_elementwise("elementwise_add")
elementwise_sub = _make_elementwise("elementwise_sub")
elementwise_mul = _make_elementwise("elementwise_mul")
elementwise_div = _make_elementwise("elementwise_div")
elementwise_max = _make_elementwise("elementwise_max")
elementwise_min = _make_elementwise("elementwise_min")
elementwise_pow = _make_elementwise("elementwise_pow")
elementwise_mod = _make_elementwise("elementwise_mod")
elementwise_floordiv = _make_elementwise("elementwise_floordiv")


def _make_reduce(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, input=input, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is None:
            attrs = {"dim": [0], "reduce_all": True, "keep_dim": keep_dim}
        else:
            if not isinstance(dim, (list, tuple)):
                dim = [dim]
            attrs = {"dim": list(dim), "reduce_all": False,
                     "keep_dim": keep_dim}
        helper.append_op(op_type, {"X": input}, {"Out": out}, attrs)
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")
reduce_all = _make_reduce("reduce_all")
reduce_any = _make_reduce("reduce_any")


def mean(x, name=None):
    helper = LayerHelper("mean", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", {"X": x}, {"Out": out}, {})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    helper = LayerHelper("matmul", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", {"X": x, "Y": y}, {"Out": out},
                     {"transpose_X": transpose_x,
                      "transpose_Y": transpose_y, "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", {"X": x, "Y": y}, {"Out": out},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def dot(x, y, name=None):
    helper = LayerHelper("dot", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("dot", {"X": x, "Y": y}, {"Out": out}, {})
    return out


# ---------------------------------------------------------------------------
# shape manipulation wrappers
# ---------------------------------------------------------------------------
def reshape(x, shape, actual_shape=None, act=None, inplace=False,
            name=None):
    helper = LayerHelper("reshape2", input=x, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reshape2", {"X": x}, {"Out": out},
                     {"shape": list(shape)})
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("squeeze2", {"X": input}, {"Out": out},
                     {"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("unsqueeze2", {"X": input}, {"Out": out},
                     {"axes": list(axes)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("transpose2", {"X": x}, {"Out": out},
                     {"axis": list(perm)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("flatten2", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", input=input[0], name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", {"X": input}, {"Out": out}, {"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", input=input, name=name)
    axis = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": axis}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections),
                 "axis": axis}
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op("split", {"X": input}, {"Out": outs}, attrs)
    return outs


def stack(x, axis=0):
    if not isinstance(x, (list, tuple)):
        x = [x]
    helper = LayerHelper("stack", input=x[0])
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", {"X": x}, {"Y": out} if False else
                     {"Out": out}, {"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack", input=x)
    n = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(n)]
    helper.append_op("unstack", {"X": x}, {"Y": outs}, {"axis": axis})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", {"X": x}, {"Out": out},
                     {"expand_times": list(expand_times)})
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand_as",
                     {"X": x, "target_tensor": target_tensor},
                     {"Out": out}, {})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", {"Input": input}, {"Out": out},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends)})
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("strided_slice", {"Input": input}, {"Out": out},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends), "strides": list(strides)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", {"X": input, "Index": index},
                     {"Out": out}, {})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", {"X": input, "Index": index},
                     {"Out": out}, {})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     {"X": input, "Ids": index, "Updates": updates},
                     {"Out": out}, {"overwrite": overwrite})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", {"X": x}, {"Out": out},
                     {"paddings": list(paddings), "pad_value": pad_value})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", {"X": input}, {"Out": out},
                     {"paddings": list(paddings), "mode": mode,
                      "pad_value": pad_value, "data_format": data_format})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("crop", {"X": x}, {"Out": out},
                     {"shape": list(shape), "offsets": list(offsets or
                      [0] * len(shape))})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot", input=input)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", {"X": input}, {"Out": out},
                     {"depth": depth})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", input=input, name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("top_k", {"X": input},
                     {"Out": values, "Indices": indices}, {"k": k})
    return values, indices


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("argsort", {"X": input},
                     {"Out": out, "Indices": ids}, {"axis": axis})
    return out, ids


def argmax(x, axis=0):
    helper = LayerHelper("arg_max", input=x)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min", input=x)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_min", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def where(condition, x=None, y=None):
    helper = LayerHelper("where", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where", {"Condition": condition, "X": x, "Y": y},
                     {"Out": out}, {})
    return out


def multiplex(inputs, index):
    helper = LayerHelper("multiplex", input=inputs[0])
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op("multiplex", {"X": inputs, "Ids": index},
                     {"Out": out}, {})
    return out


# ---------------------------------------------------------------------------
# scalar / unary wrappers
# ---------------------------------------------------------------------------
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper("scale", input=x, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", {"X": x}, {"Out": out},
                     {"scale": scale, "bias": bias,
                      "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def cast(x, dtype):
    helper = LayerHelper("cast", input=x)
    dtype = as_datatype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", {"X": x}, {"Out": out},
                     {"out_dtype": dtype.value})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", {"X": x}, {"Out": out},
                     {"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip_by_norm", {"X": x}, {"Out": out},
                     {"max_norm": max_norm})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("l2_normalize", {"X": x},
                     {"Out": out, "Norm": norm},
                     {"axis": axis, "epsilon": epsilon})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("lrn", {"X": input}, {"Out": out, "MidOut": mid},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("relu", {"X": x}, {"Out": out}, {})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("leaky_relu", {"X": x}, {"Out": out},
                     {"alpha": alpha})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", input=x, param_attr=param_attr,
                         name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    else:
        alpha_shape = [1] + list(x.shape[1:])
    alpha = helper.create_parameter(
        helper.param_attr, alpha_shape, x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", {"X": x, "Alpha": alpha}, {"Out": out},
                     {"mode": mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("maxout", {"X": x}, {"Out": out},
                     {"groups": groups})
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper("swish", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("swish", {"X": x}, {"Out": out}, {"beta": beta})
    return out


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    helper = LayerHelper("hard_swish", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("hard_swish", {"X": x}, {"Out": out},
                     {"threshold": threshold, "scale": scale,
                      "offset": offset})
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    helper = LayerHelper("hard_sigmoid", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("hard_sigmoid", {"X": x}, {"Out": out},
                     {"slope": slope, "offset": offset})
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper("elu", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("elu", {"X": x}, {"Out": out}, {"alpha": alpha})
    return out


def relu6(x, threshold=6.0, name=None):
    helper = LayerHelper("relu6", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("relu6", {"X": x}, {"Out": out},
                     {"threshold": threshold})
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pow", {"X": x}, {"Out": out}, {"factor": factor})
    return out


def soft_relu(x, threshold=40.0, name=None):
    helper = LayerHelper("soft_relu", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("soft_relu", {"X": x}, {"Out": out},
                     {"threshold": threshold})
    return out


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    helper = LayerHelper("brelu", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("brelu", {"X": x}, {"Out": out},
                     {"t_min": t_min, "t_max": t_max})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", {"X": input}, {"Out": out},
                     {"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_softmax", {"X": input}, {"Out": out},
                     {"axis": axis})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", input=x)
    # integer counters: a python-float step (the fluid-parity 1.0
    # default) would promote the value to float under JAX weak typing
    # and break lax.while_loop carry dtypes (analysis checker PTA020)
    # -- coerce integral steps to int so counters stay counters
    dt = getattr(x, "dtype", None)
    dt = getattr(dt, "value", dt)
    if isinstance(value, float) and isinstance(dt, str) \
            and dt.startswith(("int", "uint")) and value.is_integer():
        value = int(value)
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", {"X": x}, {"Out": out},
                     {"step": value})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like", input=x)
    out = out or helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", {"X": x}, {"Out": out}, {})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("fill_any_like", input=x)
    out = out or helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_any_like", {"X": x}, {"Out": out},
                     {"value": 1.0})
    return out


def shape(input):
    helper = LayerHelper("shape", input=input)
    out = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("shape", {"Input": input}, {"Out": out}, {})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op("reverse", {"X": x}, {"Out": out},
                     {"axis": list(axis)})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like", input=input)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random_batch_size_like", {"Input": input},
                     {"Out": out},
                     {"shape": list(shape), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx, "min": min,
                      "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random", {}, {"Out": out},
                     {"shape": list(shape), "mean": mean, "std": std,
                      "seed": seed, "dtype": as_datatype(dtype).value})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id", input=x)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("sampling_id", {"X": x}, {"Out": out},
                     {"min": min, "max": max, "seed": seed})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum", input=input[0])
    out = out or helper.create_variable_for_type_inference(
        input[0].dtype)
    helper.append_op("sum", {"X": input}, {"Out": out}, {})
    return out


sum = sums


# ---------------------------------------------------------------------------
# vision ops -- thin wrappers; kernels in ops/vision_ops.py
# ---------------------------------------------------------------------------
def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", align_corners=True, align_mode=1):
    helper = LayerHelper("interpolate", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if out_shape is None:
        h, w = input.shape[2], input.shape[3]
        out_shape = [int(h * scale), int(w * scale)]
    helper.append_op("interpolate", {"X": input}, {"Out": out},
                     {"out_h": out_shape[0], "out_w": out_shape[1],
                      "interp_method": resample.lower(),
                      "align_corners": align_corners,
                      "align_mode": align_mode})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        align_corners)


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler", {"X": x, "Grid": grid},
                     {"Output": out}, {})
    return out


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", input=theta, name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    attrs = {}
    if isinstance(out_shape, (list, tuple)):
        attrs["output_shape"] = [int(v) for v in out_shape]
        helper.append_op("affine_grid", {"Theta": theta},
                         {"Output": out}, attrs)
    else:
        helper.append_op("affine_grid",
                         {"Theta": theta, "OutputShape": out_shape},
                         {"Output": out}, attrs)
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None):
    helper = LayerHelper("affine_channel", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("affine_channel",
                     {"X": x, "Scale": scale, "Bias": bias},
                     {"Out": out}, {"data_layout": data_layout})
    return out


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("shuffle_channel", {"X": x}, {"Out": out},
                     {"group": group})
    return out


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pixel_shuffle", {"X": x}, {"Out": out},
                     {"upscale_factor": upscale_factor})
    return out


def pixel_unshuffle(x, downscale_factor):
    helper = LayerHelper("pixel_unshuffle", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pixel_unshuffle", {"X": x}, {"Out": out},
                     {"downscale_factor": downscale_factor})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper("roi_pool", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax_ = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("roi_pool", {"X": input, "ROIs": rois},
                     {"Out": out, "Argmax": argmax_},
                     {"pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "spatial_scale": spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("roi_align", {"X": input, "ROIs": rois},
                     {"Out": out},
                     {"pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "spatial_scale": spatial_scale,
                      "sampling_ratio": sampling_ratio})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    helper = LayerHelper("psroi_pool", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("psroi_pool", {"X": input, "ROIs": rois},
                     {"Out": out},
                     {"output_channels": output_channels,
                      "spatial_scale": spatial_scale,
                      "pooled_height": pooled_height,
                      "pooled_width": pooled_width})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", input=input, param_attr=param_attr,
                         act=act)
    w = helper.create_parameter(
        helper.param_attr, [future_context_size + 1, input.shape[-1]],
        input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", {"X": input, "Filter": w},
                     {"Out": out}, {})
    return helper.append_activation(out)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("temporal_shift", {"X": x}, {"Out": out},
                     {"seg_num": seg_num, "shift_ratio": shift_ratio})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1,
           name=None):
    helper = LayerHelper("unfold", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("unfold", {"X": x}, {"Y": out},
                     {"kernel_sizes": _pair(kernel_sizes),
                      "strides": _pair(strides),
                      "paddings": _pair(paddings),
                      "dilations": _pair(dilations)})
    return out


def deformable_conv(input, offset, mask=None, num_filters=None,
                    filter_size=None, stride=1, padding=0, dilation=1,
                    groups=1, deformable_groups=1, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=None,
                    act=None, name=None):
    """Deformable conv v1 (mask=None) / v2 (modulated, with mask).
    Beyond-reference capability (no op in this reference tree; API
    modeled on later fluid surfaces). `offset` is
    [B, 2*deformable_groups*kh*kw, Ho, Wo] with (dy, dx) per tap;
    `mask` is [B, deformable_groups*kh*kw, Ho, Wo]. `modulated`
    defaults to inferring v1/v2 from mask presence; passing it
    explicitly must agree with the mask (silently dropping a mask or
    degrading v2 to v1 would be wrong numbers, not an error).
    im2col_step is accepted for API parity and ignored (the TPU
    lowering samples all taps in one gather — see ops/nn_ops.py
    deformable_conv)."""
    if modulated is None:
        modulated = mask is not None
    if modulated and mask is None:
        raise ValueError("deformable_conv: modulated=True (v2) needs "
                         "a mask input")
    if not modulated and mask is not None:
        raise ValueError("deformable_conv: a mask was given but "
                         "modulated=False would silently ignore it; "
                         "pass modulated=True or drop the mask")
    helper = LayerHelper("deformable_conv", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    num_channels = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups, fs[0], fs[1]]
    std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, filter_shape, input.dtype,
        default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "Offset": offset, "Filter": w}
    if mask is not None:
        ins["Mask"] = mask
    helper.append_op(
        "deformable_conv", ins, {"Output": out},
        {"strides": _pair(stride), "paddings": _pair(padding),
         "dilations": _pair(dilation), "groups": groups,
         "deformable_groups": deformable_groups})
    out = _conv_bias(helper, out)
    return helper.append_activation(out)


def switch_moe(input, num_experts, d_inner, top_k=1,
               capacity_factor=2.0, param_attr=None, name=None,
               return_drop_frac=False):
    """Switch/GShard mixture-of-experts FFN (beyond-reference; routing
    math + expert-parallel dataflow in parallel/moe.py, lowered by the
    `switch_moe` op). Returns (out, aux_loss): add
    ``aux_loss * coeff`` (Switch uses coeff=0.01) onto the training
    loss or routing collapses onto one expert.
    With ``return_drop_frac=True`` returns (out, aux_loss, drop_frac)
    where drop_frac [1] is the fraction of tokens that received NO
    expert slot this step — fetch it to monitor silent over-capacity
    drops (it costs nothing when unfetched; XLA dead-codes it).

    input: [..., D]; experts are [D, d_inner] -> [d_inner, D] relu
    MLPs. Under `with expert_parallel(mesh):` the op runs all_to_all
    expert-parallel over the 'ep' mesh axis."""
    helper = LayerHelper("switch_moe", input=input,
                         param_attr=param_attr, name=name)
    d = input.shape[-1]
    prefix = name or helper.name
    std = (2.0 / d) ** 0.5

    def _attr(suffix):
        from ..param_attr import ParamAttr
        import copy as _copy

        a = ParamAttr._to_attr(param_attr)
        a = ParamAttr() if a is None else _copy.copy(a)
        a.name = f"{prefix}_{suffix}" if a.name is None \
            else f"{a.name}_{suffix}"
        return a

    wg = helper.create_parameter(
        _attr("gate_w"), [d, num_experts], input.dtype,
        default_initializer=NormalInitializer(0.0, 0.02))
    w1 = helper.create_parameter(
        _attr("expert_w1"), [num_experts, d, d_inner], input.dtype,
        default_initializer=NormalInitializer(0.0, std))
    w2 = helper.create_parameter(
        _attr("expert_w2"), [num_experts, d_inner, d], input.dtype,
        default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(input.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    drop = helper.create_variable_for_type_inference("float32")
    drop.stop_gradient = True
    helper.append_op(
        "switch_moe",
        {"X": input, "GateW": wg, "W1": w1, "W2": w2},
        {"Out": out, "AuxLoss": aux, "DropFrac": drop},
        {"top_k": int(top_k), "capacity_factor": float(capacity_factor)})
    if return_drop_frac:
        return out, aux, drop
    return out, aux


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("im2sequence", {"X": input}, {"Out": out},
                     {"kernels": _pair(filter_size),
                      "strides": _pair(stride),
                      "paddings": _pair(padding) + _pair(padding)})
    return out


# --- sequence/RNN/decoding layers live in rnn.py & sequence.py; imported
# lazily at the bottom to avoid circular imports -------------------------
from .sequence import (  # noqa: E402,F401
    sequence_conv, sequence_pool, sequence_softmax, sequence_expand,
    sequence_concat, sequence_first_step, sequence_last_step,
    sequence_reshape, sequence_pad, sequence_unpad, sequence_reverse,
    sequence_slice, sequence_enumerate, sequence_expand_as,
    sequence_scatter)
from .rnn import (  # noqa: E402,F401
    lstm, dynamic_lstm, dynamic_gru, gru_unit, lstm_unit, beam_search,
    beam_search_decode, edit_distance, ctc_greedy_decoder, warpctc, nce,
    hsigmoid, sampled_softmax_with_cross_entropy, linear_chain_crf,
    linear_chain_crf_raw, crf_decoding, crf_decoding_raw)


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


def _triple(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v, v]


def attention_block(x, n_heads, causal=False, scale=None,
                    param_attr_qkv=None, param_attr_out=None,
                    name=None):
    """Whole-layer fused self-attention sub-layer (no dropout, no
    projection biases, residual outside): ONE op replacing the
    qkv-fc/split/reshape/attention/reshape/out-fc sequence so the
    pallas kernel (ops/pallas/attention_block.py) can keep every
    intermediate in VMEM. Route multi_head_attention through it with
    PADDLE_TPU_FUSE_ATTN_BLOCK=1 (A/B knob; PERF.md)."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("attention_block", input=x,
                         param_attr=param_attr_qkv, name=name)
    d = int(x.shape[-1])
    if d % n_heads:
        raise ValueError(
            f"attention_block: d_model {d} not divisible by "
            f"n_heads {n_heads}")
    w_qkv = helper.create_parameter(
        ParamAttr._to_attr(param_attr_qkv), [d, 3 * d], x.dtype)
    w_o = helper.create_parameter(
        ParamAttr._to_attr(param_attr_out), [d, d], x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "attention_block", {"X": x, "WQKV": w_qkv, "WO": w_o},
        {"Out": out},
        {"n_heads": int(n_heads),
         "scale": float(scale if scale is not None
                        else (d // n_heads) ** -0.5),
         "causal": bool(causal)})
    return out


__all__.append("attention_block")


def ffn_block(x, d_inner, param_attr_fc1=None, bias_attr_fc1=None,
              param_attr_fc2=None, bias_attr_fc2=None, name=None):
    """Whole-layer fused position-wise MLP (relu between two fcs, no
    dropout): ONE op replacing the mul/add/relu/mul/add sequence so
    the pallas kernel (ops/pallas/ffn_block.py) keeps the [T, d_inner]
    hidden in VMEM. Routed from models/transformer._ffn by
    PADDLE_TPU_FUSE_ATTN_BLOCK=1."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("ffn_block", input=x,
                         param_attr=param_attr_fc1, name=name)
    d = int(x.shape[-1])
    w1 = helper.create_parameter(
        ParamAttr._to_attr(param_attr_fc1), [d, d_inner], x.dtype)
    b1 = helper.create_parameter(
        ParamAttr._to_attr(bias_attr_fc1), [d_inner], x.dtype,
        is_bias=True)
    w2 = helper.create_parameter(
        ParamAttr._to_attr(param_attr_fc2), [d_inner, d], x.dtype)
    b2 = helper.create_parameter(
        ParamAttr._to_attr(bias_attr_fc2), [d], x.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "ffn_block",
        {"X": x, "W1": w1, "B1": b1, "W2": w2, "B2": b2},
        {"Out": out}, {})
    return out


__all__.append("ffn_block")


def attention(q, k, v, causal=False, scale=None, dropout_rate=0.0,
              is_test=False, layout="bhtd", name=None):
    """Fused scaled-dot-product attention -- the framework's
    flash-attention entry point (Pallas kernel on TPU). layout='bthd'
    takes [B,T,H,D] straight from the head-split reshape, skipping the
    physical head transpose (see ops/nn_ops.py attention)."""
    helper = LayerHelper("attention", input=q, name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op("attention", {"Q": q, "K": k, "V": v},
                     {"Out": out},
                     {"causal": causal, "scale": scale,
                      "dropout_rate": dropout_rate,
                      "is_test": is_test, "layout": layout})
    return out


__all__.append("attention")
__all__.append("switch_moe")
__all__.extend(["linear_chain_crf", "linear_chain_crf_raw",
                "crf_decoding", "crf_decoding_raw"])


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """reference layers/nn.py stanh -> activation_op.cc STanh."""
    helper = LayerHelper("stanh", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("stanh", {"X": x}, {"Out": out},
                     {"scale_a": scale_a, "scale_b": scale_b})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", name=None):
    """reference layers/nn.py adaptive_pool3d (NCDHW)."""
    helper = LayerHelper("adaptive_pool3d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    size = pool_size if isinstance(pool_size, (list, tuple)) else \
        [pool_size] * 3
    helper.append_op("adaptive_pool3d", {"X": input}, {"Out": out},
                     {"pooling_size": list(size),
                      "pooling_type": pool_type})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0,
                                    std=1.0, seed=0, dtype="float32"):
    """reference layers/nn.py gaussian_random_batch_size_like."""
    helper = LayerHelper("gaussian_random_batch_size_like",
                         input=input)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random_batch_size_like",
                     {"Input": input}, {"Out": out},
                     {"shape": list(shape),
                      "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx, "mean": mean,
                      "std": std, "seed": seed})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference layers/nn.py autoincreased_step_counter: a persistable
    int64 counter bumped once per executor run (the global-step var the
    LR schedules build on)."""
    helper = LayerHelper("step_counter")
    name = counter_name or "@STEP_COUNTER@"
    block = helper.main_program.global_block
    counter = block.create_var(name=name, shape=(1,), dtype="int64",
                               persistable=True, stop_gradient=True)
    sblock = helper.startup_program.global_block
    svar = sblock.create_var(name=name, shape=(1,), dtype="int64",
                             persistable=True)
    if not any(name in op.output_arg_names for op in sblock.ops):
        from ..initializer import ConstantInitializer

        ConstantInitializer(float(begin - step))(svar, sblock)
    cur = helper.main_program.current_block()
    if not any(name in op.output_arg_names and op.type == "increment"
               for op in cur.ops):
        # int step: a python float would promote the int64 counter to
        # float32 under JAX type rules on the first x + attr
        cur.append_op("increment", {"X": counter}, {"Out": counter},
                      {"step": int(step)})
    return counter


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference layers/nn.py image_resize_short: scale so the SHORT
    edge becomes out_short_len, keeping aspect ratio (static shapes:
    computed at build time from the declared H/W)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    ratio = float(out_short_len) / float(short)
    out_shape = [int(round(h * ratio)), int(round(w * ratio))]
    return image_resize(input, out_shape=out_shape, resample=resample)


def lod_reset(x, y=None, target_lod=None):
    """reference layers/nn.py lod_reset -> lod_reset_op.cc. Under the
    padded+@SEQ_LEN design the data is unchanged; the new lengths come
    from y's companion (or target_lod converted by the caller)."""
    helper = LayerHelper("lod_reset", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x}
    if y is not None:
        ins["Y"] = y
    helper.append_op("lod_reset", ins, {"Out": out},
                     {"target_lod": list(target_lod or [])})
    from .sequence import SEQ_LEN_SUFFIX

    block = out.block
    src = (y.name if y is not None else x.name) + SEQ_LEN_SUFFIX
    if block.has_var(src):
        dst = out.name + SEQ_LEN_SUFFIX
        helper.append_op("assign", {"X": src}, {"Out": dst}, {})
        block.create_var(name=dst, shape=(-1,), dtype="int32",
                         stop_gradient=True)
    return out


def mean_iou(input, label, num_classes):
    """reference layers/nn.py mean_iou -> mean_iou_op.cc."""
    helper = LayerHelper("mean_iou", input=input)
    miou = helper.create_variable_for_type_inference("float32", True)
    wrong = helper.create_variable_for_type_inference("float32", True)
    correct = helper.create_variable_for_type_inference("float32",
                                                        True)
    helper.append_op("mean_iou",
                     {"Predictions": input, "Labels": label},
                     {"OutMeanIou": miou, "OutWrong": wrong,
                      "OutCorrect": correct},
                     {"num_classes": num_classes})
    return miou, wrong, correct


def similarity_focus(input, axis, indexes, name=None):
    """reference layers/nn.py similarity_focus ->
    similarity_focus_op.cc."""
    helper = LayerHelper("similarity_focus", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("similarity_focus", {"X": input}, {"Out": out},
                     {"axis": axis, "indexes": list(indexes)})
    return out


def merge_selected_rows(x, name=None):
    """reference layers/nn.py merge_selected_rows: sum duplicate rows
    of a SelectedRows pair (rows var + values var, the sparse-grad
    representation — x is the values var, x@ROWS its companion)."""
    helper = LayerHelper("merge_selected_rows", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    rows_out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("merge_selected_rows",
                     {"Rows": x.name + "@ROWS", "Values": x},
                     {"OutRows": rows_out, "Out": out}, {})
    return out


def get_tensor_from_selected_rows(x, height=None, name=None):
    """reference layers/nn.py get_tensor_from_selected_rows: scatter a
    SelectedRows (values var + @ROWS companion) into a dense tensor."""
    helper = LayerHelper("get_tensor_from_selected_rows", input=x,
                         name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("get_tensor_from_selected_rows",
                     {"Rows": x.name + "@ROWS", "Values": x},
                     {"Out": out},
                     {"height": height or int(x.shape[0])})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """reference layers/nn.py tree_conv -> tree_conv_op.cc (TBCNN)."""
    helper = LayerHelper("tree_conv", input=nodes_vector,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = nodes_vector.dtype
    feature_size = int(nodes_vector.shape[-1])
    w = helper.create_parameter(
        helper.param_attr, [feature_size, 3, output_size, num_filters],
        dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("tree_conv",
                     {"NodesVector": nodes_vector,
                      "EdgeSet": edge_set, "Filter": w},
                     {"Out": out}, {"max_depth": max_depth})
    if helper.bias_attr is not False:
        pre_act = helper.append_bias_op(out, dim_start=3)
    else:
        pre_act = out
    return helper.append_activation(pre_act)


__all__.extend([
    "stanh", "adaptive_pool3d", "gaussian_random_batch_size_like",
    "autoincreased_step_counter", "image_resize_short", "lod_reset",
    "mean_iou", "similarity_focus", "merge_selected_rows",
    "get_tensor_from_selected_rows", "tree_conv"])
