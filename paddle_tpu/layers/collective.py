"""Collective communication layers.

Parity: reference python/paddle/fluid/layers/collective.py:19
(`_allreduce` -- the private layer the nccl2-mode transpiler and
dygraph multi-process path append per gradient). The op lowers to an
in-graph cross-process reduction (ops/dist_ops.py allreduce);
single-process it is identity, and inside a pjit'd data-parallel
block the mesh psum (parallel/, CompiledProgram) is the idiomatic
path -- this layer exists for reference program compatibility."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["_allreduce"]


def _allreduce(x, out=None, reduce_type="sum", sync_mode=False):
    helper = LayerHelper("allreduce", input=x)
    if reduce_type not in ("sum", "mean", "max", "min", "prod"):
        raise TypeError(f"reduce_type {reduce_type!r} is not supported")
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=getattr(x, "dtype", None))
    # Variable objects (not names): LayerHelper routes them through
    # BOTH graph append and the dygraph eager trace
    helper.append_op("allreduce", {"X": [x]}, {"Out": [out]},
                     {"reduce_type": reduce_type,
                      "sync_mode": sync_mode})
    return out
