"""Host-bridge layers: py_func, chunk_eval, Go.

Parity: reference python/paddle/fluid/layers/nn.py py_func (+
operators/py_func_op.cc), layers/nn.py chunk_eval, and the Go op
(operators/csp/go_op.cc via fluid.layers.Go-era API).
"""
from __future__ import annotations

from ..layer_helper import LayerHelper
from ..core.program import default_main_program

__all__ = ["py_func", "chunk_eval", "Go"]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Call a Python function as a graph op (reference layers/nn.py
    py_func). `out` vars must be pre-created with known shapes/dtypes
    (create via program.current_block().create_var), like the
    reference requires."""
    from ..ops.host_ops import register_py_func

    if not isinstance(x, (list, tuple)):
        x = [x]
    if not isinstance(out, (list, tuple)):
        out = [out]
    fid = register_py_func(func)
    bid = register_py_func(backward_func) if backward_func else -1
    skip = [getattr(v, "name", v)
            for v in (skip_vars_in_backward_input or [])]
    helper = LayerHelper("py_func", input=x[0])
    helper.append_op(
        "py_func", {"X": list(x)}, {"Out": list(out)},
        {"forward_callable_id": fid, "backward_callable_id": bid,
         "backward_skip_vars": skip})
    return out if len(out) > 1 else out[0]


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """reference layers/nn.py chunk_eval -> chunk_eval_op.cc. Returns
    (precision, recall, f1, num_infer, num_label, num_correct)."""
    helper = LayerHelper("chunk_eval", input=input)
    precision = helper.create_variable_for_type_inference("float32",
                                                          True)
    recall = helper.create_variable_for_type_inference("float32", True)
    f1 = helper.create_variable_for_type_inference("float32", True)
    num_infer = helper.create_variable_for_type_inference("int64",
                                                          True)
    num_label = helper.create_variable_for_type_inference("int64",
                                                          True)
    num_correct = helper.create_variable_for_type_inference("int64",
                                                            True)
    ins = {"Inference": input, "Label": label}
    if seq_length is None:
        # auto-wire the padded-batch length companion (the framework's
        # @SEQ_LEN convention, layers/sequence.py) so padded tails are
        # not scored as chunks
        cand = input.name + "@SEQ_LEN"
        if input.block.has_var(cand):
            ins["SeqLength"] = cand
    else:
        ins["SeqLength"] = seq_length
    helper.append_op(
        "chunk_eval", ins,
        {"Precision": precision, "Recall": recall, "F1-Score": f1,
         "NumInferChunks": num_infer, "NumLabelChunks": num_label,
         "NumCorrectChunks": num_correct},
        {"chunk_scheme": chunk_scheme,
         "num_chunk_types": num_chunk_types,
         "excluded_chunk_types": list(excluded_chunk_types or [])})
    return precision, recall, f1, num_infer, num_label, num_correct


class Go:
    """Goroutine block (reference operators/csp/go_op.cc):

        with fluid.layers.Go(inputs=[x]):
            ... ops captured into the concurrent sub-block ...
    """

    def __init__(self, inputs=None, name=None):
        self._inputs = list(inputs or [])
        self._program = default_main_program()

    def __enter__(self):
        self._block = self._program.create_block()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._program.rollback()
        if exc_type is not None:
            return False
        parent = self._program.current_block()
        # auto-capture every external read (params, constants...) so
        # the goroutine's snapshot env is self-contained
        declared = [v.name for v in self._inputs]
        produced = set()
        for op in self._block.ops:
            for n in op.input_arg_names:
                if (n not in produced and n not in declared
                        and n not in self._block.vars):
                    declared.append(n)
            produced.update(op.output_arg_names)
        parent.append_op(
            "go", {"X": declared}, {}, {"sub_block": self._block})
        return True
