"""Metric layers (reference python/paddle/fluid/layers/metric_op.py)."""
from __future__ import annotations

from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    """topk accuracy (reference metric_op.py accuracy)."""
    helper = LayerHelper("accuracy", input=input)
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64",
                                                             True)
    helper.append_op("top_k", {"X": input},
                     {"Out": topk_out, "Indices": topk_indices},
                     {"k": k})
    acc_out = helper.create_variable_for_type_inference("float32", True)
    correct = correct or helper.create_variable_for_type_inference(
        "int32", True)
    total = total or helper.create_variable_for_type_inference(
        "int32", True)
    helper.append_op(
        "accuracy",
        {"Out": topk_out, "Indices": topk_indices, "Label": label},
        {"Accuracy": acc_out, "Correct": correct, "Total": total}, {})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    helper = LayerHelper("auc", input=input)
    stat_pos = helper.create_global_variable(
        [num_thresholds + 1], "float32", persistable=True)
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0.0))
    stat_neg = helper.create_global_variable(
        [num_thresholds + 1], "float32", persistable=True)
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        "auc",
        {"Predict": input, "Label": label, "StatPos": stat_pos,
         "StatNeg": stat_neg},
        {"AUC": auc_out, "StatPosOut": stat_pos,
         "StatNegOut": stat_neg},
        {"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg]
