"""RNN / decoding / sampled-loss layers.

Parity targets: reference operators/lstm_op.cc, gru_op.cc, lstm_unit_op.cc,
gru_unit_op.cc, cudnn_lstm_op.cu.cc, beam_search_op.cc,
beam_search_decode_op.cc, edit_distance_op.cc, warpctc_op.cc, nce_op.cc,
hierarchical_sigmoid_op.cc, sample_logits_op.cc.

RNNs run over the padded [batch, time, dim] + @SEQ_LEN representation and
lower to lax.scan (compiled once, unrolled by XLA into a fused loop) --
replacing the reference's per-timestep dynamic-RNN interpreter
(recurrent_op.cc) and cuDNN LSTM descriptor machinery.
"""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper
from .sequence import bind_seq_len, seq_len_of, SEQ_LEN_SUFFIX

__all__ = ["lstm", "dynamic_lstm", "dynamic_gru", "gru_unit",
           "lstm_unit", "beam_search", "beam_search_decode",
           "edit_distance", "ctc_greedy_decoder", "warpctc", "nce",
           "hsigmoid", "sampled_softmax_with_cross_entropy",
           "linear_chain_crf", "linear_chain_crf_raw", "crf_decoding",
           "crf_decoding_raw"]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """reference lstm_op.cc: input is pre-projected x·W_x [N,T,4H]."""
    helper = LayerHelper("dynamic_lstm", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    hidden = size // 4
    w = helper.create_parameter(helper.param_attr, [hidden, 4 * hidden],
                                dtype)
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    b = helper.create_parameter(helper.bias_attr, [1, bias_size], dtype,
                                is_bias=True)
    h_out = helper.create_variable_for_type_inference(dtype)
    c_out = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": input, "Weight": w, "Bias": b,
           "SeqLen": seq_len_of(input)}
    if h_0 is not None:
        ins["H0"] = h_0
    if c_0 is not None:
        ins["C0"] = c_0
    helper.append_op("lstm", ins, {"Hidden": h_out, "Cell": c_out},
                     {"use_peepholes": use_peepholes,
                      "is_reverse": is_reverse,
                      "gate_activation": gate_activation,
                      "cell_activation": cell_activation,
                      "candidate_activation": candidate_activation})
    block = h_out.block
    for o in (h_out, c_out):
        lname = o.name + SEQ_LEN_SUFFIX
        helper.append_op("assign", {"X": input.name + SEQ_LEN_SUFFIX},
                         {"Out": lname}, {})
        block.create_var(name=lname, shape=(-1,), dtype="int32",
                         stop_gradient=True)
    return h_out, c_out


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """cuDNN-style stacked LSTM (reference cudnn_lstm_op.cu.cc) -- here a
    stack of scan-based layers; is_bidirec runs a reversed twin per
    layer and concats the two hidden sequences (the cuDNN
    CUDNN_BIDIRECTIONAL semantics)."""
    helper = LayerHelper("cudnn_lstm", input=input, name=name)
    from . import nn

    x = input
    h_last = None
    c_last = None
    for layer in range(num_layers):
        hs, cs = [], []
        for is_rev in ((False, True) if is_bidirec else (False,)):
            proj = nn.fc(x, 4 * hidden_size, num_flatten_dims=2,
                         bias_attr=None)
            bind_seq_len(proj, x)
            h, c = dynamic_lstm(proj, 4 * hidden_size,
                                use_peepholes=False,
                                is_reverse=is_rev)
            hs.append(h)
            cs.append(c)
        if is_bidirec:
            h = nn.concat(hs, axis=-1)
            c = nn.concat(cs, axis=-1)
            # feature concat preserves the padded-batch layout; keep
            # the @SEQ_LEN companion flowing into the next layer
            bind_seq_len(h, hs[0])
            bind_seq_len(c, cs[0])
        else:
            h, c = hs[0], cs[0]
        x = h
        h_last, c_last = h, c
        # cuDNN applies dropout BETWEEN layers only (cudnn_rnn_cache.h
        # dropout descriptor; same guard as the cudnn_lstm op) — never
        # to the final output / last states
        if dropout_prob and not is_test and layer < num_layers - 1:
            x = nn.dropout(h, dropout_prob,
                           dropout_implementation="upscale_in_train")
            bind_seq_len(x, h)
    return x, h_last, c_last


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, name=None):
    """reference gru_op.cc: input pre-projected [N,T,3H]."""
    helper = LayerHelper("dynamic_gru", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = input.dtype
    w = helper.create_parameter(helper.param_attr, [size, 3 * size],
                                dtype)
    b = helper.create_parameter(helper.bias_attr, [1, 3 * size], dtype,
                                is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": input, "Weight": w, "Bias": b,
           "SeqLen": seq_len_of(input)}
    if h_0 is not None:
        ins["H0"] = h_0
    helper.append_op("gru", ins, {"Hidden": out},
                     {"is_reverse": is_reverse,
                      "gate_activation": gate_activation,
                      "activation": candidate_activation})
    block = out.block
    lname = out.name + SEQ_LEN_SUFFIX
    helper.append_op("assign", {"X": input.name + SEQ_LEN_SUFFIX},
                     {"Out": lname}, {})
    block.create_var(name=lname, shape=(-1,), dtype="int32",
                     stop_gradient=True)
    return out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    helper = LayerHelper("gru_unit", input=input, param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    h = size // 3
    w = helper.create_parameter(helper.param_attr, [h, 3 * h], dtype)
    b = helper.create_parameter(helper.bias_attr, [1, 3 * h], dtype,
                                is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gru_unit",
                     {"Input": input, "HiddenPrev": hidden, "Weight": w,
                      "Bias": b},
                     {"Gate": gate, "ResetHiddenPrev": reset_h,
                      "Hidden": updated},
                     {"activation": activation,
                      "gate_activation": gate_activation,
                      "origin_mode": origin_mode})
    return updated, reset_h, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("lstm_unit", input=x_t, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    from . import nn

    size = cell_t_prev.shape[-1]
    concat_in = nn.concat([x_t, hidden_t_prev], axis=1)
    fc_out = nn.fc(concat_in, 4 * size, param_attr=param_attr,
                   bias_attr=bias_attr)
    cell = helper.create_variable_for_type_inference(x_t.dtype)
    hidden = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op("lstm_unit",
                     {"X": fc_out, "C_prev": cell_t_prev},
                     {"C": cell, "H": hidden},
                     {"forget_bias": forget_bias})
    return hidden, cell


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    helper = LayerHelper("beam_search", input=ids, name=name)
    sel_ids = helper.create_variable_for_type_inference("int64", True)
    sel_scores = helper.create_variable_for_type_inference(
        scores.dtype, True)
    parent = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(
        "beam_search",
        {"pre_ids": pre_ids, "pre_scores": pre_scores, "ids": ids,
         "scores": scores},
        {"selected_ids": sel_ids, "selected_scores": sel_scores,
         "parent_idx": parent},
        {"beam_size": beam_size, "end_id": end_id, "level": level,
         "is_accumulated": is_accumulated})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parents=None):
    """Backtrack stacked beam selections (reference
    beam_search_decode_op.cc). The reference recovers lineage from LoD
    offsets; the static-shape port takes it as the explicit `parents`
    tensor produced by beam_search(return_parent_idx=True). Without
    `parents`, each beam is treated as its own ancestor (greedy/
    already-aligned stacks)."""
    helper = LayerHelper("beam_search_decode", input=ids, name=name)
    out_ids = helper.create_variable_for_type_inference("int64", True)
    out_scores = helper.create_variable_for_type_inference(
        scores.dtype, True)
    inputs = {"Ids": ids, "Scores": scores}
    if parents is not None:
        inputs["Parents"] = parents
    helper.append_op("beam_search_decode", inputs,
                     {"SentenceIds": out_ids,
                      "SentenceScores": out_scores},
                     {"beam_size": beam_size, "end_id": end_id})
    return out_ids, out_scores


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    helper = LayerHelper("edit_distance", input=input)
    out = helper.create_variable_for_type_inference("float32", True)
    seq_num = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("edit_distance",
                     {"Hyps": input, "Refs": label,
                      "HypsLen": seq_len_of(input),
                      "RefsLen": seq_len_of(label)},
                     {"Out": out, "SequenceNum": seq_num},
                     {"normalized": normalized})
    return out, seq_num


def ctc_greedy_decoder(input, blank, name=None):
    helper = LayerHelper("ctc_align", input=input, name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("ctc_align",
                     {"Input": input, "SeqLen": seq_len_of(input)},
                     {"Output": out},
                     {"blank": blank, "merge_repeated": True})
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            use_cudnn=False):
    helper = LayerHelper("warpctc", input=input)
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("warpctc",
                     {"Logits": input, "Label": label,
                      "LogitsLen": seq_len_of(input),
                      "LabelLen": seq_len_of(label)},
                     {"Loss": loss, "WarpCTCGrad": grad},
                     {"blank": blank, "norm_by_times": norm_by_times})
    return loss


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation (reference nce_op.cc)."""
    helper = LayerHelper("nce", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                [num_total_classes, dim], input.dtype)
    b = helper.create_parameter(helper.bias_attr,
                                [num_total_classes, 1], input.dtype,
                                is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype, True)
    slog = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "nce",
        {"Input": input, "Label": label, "Weight": w, "Bias": b},
        {"Cost": cost, "SampleLogits": sl, "SampleLabels": slog},
        {"num_total_classes": num_total_classes,
         "num_neg_samples": num_neg_samples or 10, "seed": seed,
         "sampler": {"uniform": 0, "log_uniform": 1,
                     "custom_dist": 2}.get(sampler, 0)})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """Hierarchical sigmoid (reference hierarchical_sigmoid_op.cc)."""
    helper = LayerHelper("hierarchical_sigmoid", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                [num_classes - 1, dim], input.dtype)
    b = helper.create_parameter(helper.bias_attr, [num_classes - 1, 1],
                                input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        "hierarchical_sigmoid",
        {"X": input, "Label": label, "W": w, "Bias": b},
        {"Out": out, "PreOut": pre},
        {"num_classes": num_classes})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=
                                       True, use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    helper = LayerHelper("sample_logits", input=logits)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        "sample_logits",
        {"Logits": logits, "Labels": label},
        {"Loss": loss},
        {"num_samples": num_samples, "num_true": num_true,
         "remove_accidental_hits": remove_accidental_hits,
         "seed": seed})
    return loss


def linear_chain_crf(input, label, param_attr=None, length=None):
    """Linear-chain CRF cost (reference layers/nn.py linear_chain_crf,
    linear_chain_crf_op.h). Creates the [size+2, size] transition
    parameter (row 0 start, row 1 end weights); returns the per-sequence
    negative log-likelihood to minimize."""
    helper = LayerHelper("linear_chain_crf", input=input,
                         param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, [size + 2, size], input.dtype)
    return linear_chain_crf_raw(input, transition, label, length=length)


def linear_chain_crf_raw(emission, transition, label, length=None):
    helper = LayerHelper("linear_chain_crf", input=emission)
    ll = helper.create_variable_for_type_inference(emission.dtype)
    alpha = helper.create_variable_for_type_inference(emission.dtype,
                                                      True)
    inputs = {"Emission": emission, "Transition": transition,
              "Label": label}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("linear_chain_crf", inputs,
                     {"LogLikelihood": ll, "Alpha": alpha}, {})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the transition param created by
    linear_chain_crf (reference crf_decoding_op.h); pass the same
    ParamAttr name to share it."""
    helper = LayerHelper("crf_decoding", input=input,
                         param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, [size + 2, size], input.dtype)
    return crf_decoding_raw(input, transition, label=label,
                            length=length)


def crf_decoding_raw(emission, transition, label=None, length=None):
    helper = LayerHelper("crf_decoding", input=emission)
    path = helper.create_variable_for_type_inference("int64", True)
    inputs = {"Emission": emission, "Transition": transition}
    if label is not None:
        inputs["Label"] = label
    if length is not None:
        inputs["Length"] = length
    helper.append_op("crf_decoding", inputs, {"ViterbiPath": path}, {})
    return path
