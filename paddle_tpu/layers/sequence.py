"""Sequence layers over padded-batch + explicit-length representation.

The reference's LoDTensor (reference framework/lod_tensor.h:110 + ~20 ops
under operators/sequence_ops/) carries nested offsets on a packed batch --
inherently dynamic-shaped, which XLA cannot compile. The TPU-native
representation (SURVEY.md hard part (a)) is:

    data:   dense padded [batch, max_len, ...]
    length: int32 [batch] companion var named  <name>@SEQ_LEN

Masked/segment computations replace offset walking; everything stays
static-shaped (bucket batches by max_len to bound recompiles).
DataFeeder converts fluid-style (flat_data, lod) feeds into this layout.
"""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper

__all__ = ["bind_seq_len",
           "sequence_conv", "sequence_pool", "sequence_softmax",
           "sequence_expand", "sequence_concat", "sequence_first_step",
           "sequence_last_step", "sequence_reshape", "sequence_pad",
           "sequence_unpad", "sequence_reverse", "sequence_slice",
           "sequence_enumerate", "sequence_expand_as",
           "sequence_scatter", "seq_len_of"]

SEQ_LEN_SUFFIX = "@SEQ_LEN"


def seq_len_of(x):
    """Find (or declare) the companion length var for padded sequences."""
    block = x.block
    name = x.name + SEQ_LEN_SUFFIX
    if block.has_var(name):
        return block.var(name)
    return block.create_var(name=name, shape=(-1,), dtype="int32",
                            is_data=True, stop_gradient=True)


def bind_seq_len(dst_var, src_var):
    """Propagate/declare the @SEQ_LEN companion from src to dst -- THE
    public contract for keeping padded-batch lengths attached as data
    flows through batch-preserving layers (fc over time, embedding...).
    Declares src's companion as a data var if it doesn't exist yet."""
    blk = dst_var.block
    src = src_var.name + SEQ_LEN_SUFFIX
    if not blk.has_var(src):
        blk.create_var(name=src, shape=(-1,), dtype="int32",
                       is_data=True, stop_gradient=True)
    dst = dst_var.name + SEQ_LEN_SUFFIX
    blk.append_op("assign", {"X": src}, {"Out": dst}, {})
    blk.create_var(name=dst, shape=(-1,), dtype="int32",
                   stop_gradient=True)
    return dst_var


def _bind_len(helper, out, x):
    """Propagate the length companion from x to out (same batch layout)."""
    block = out.block
    src = x.name + SEQ_LEN_SUFFIX
    if x.block.has_var(src):
        dst = out.name + SEQ_LEN_SUFFIX
        helper.append_op("assign", {"X": src}, {"Out": dst}, {})
        block.create_var(name=dst, shape=(-1,), dtype="int32",
                         stop_gradient=True)
    return out


def sequence_pool(input, pool_type, is_test=False):
    helper = LayerHelper("sequence_pool", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("sequence_pool",
                     {"X": input, "SeqLen": seq_len_of(input)},
                     {"Out": out, "MaxIndex": idx},
                     {"pooltype": pool_type.upper()})
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_softmax",
                     {"X": input, "SeqLen": seq_len_of(input)},
                     {"Out": out}, {})
    return _bind_len(helper, out, input)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, bias_attr=None, param_attr=None, act=None,
                  name=None):
    helper = LayerHelper("sequence_conv", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                               [filter_size * d, num_filters],
                               input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_conv",
                     {"X": input, "Filter": w,
                      "SeqLen": seq_len_of(input)},
                     {"Out": out},
                     {"contextLength": filter_size,
                      "contextStart": -(filter_size // 2),
                      "contextStride": filter_stride})
    out = helper.append_bias_op(out, dim_start=2)
    out = helper.append_activation(out)
    return _bind_len(helper, out, input)


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_expand",
                     {"X": x, "Y": y, "SeqLen": seq_len_of(y)},
                     {"Out": out}, {"ref_level": ref_level})
    return _bind_len(helper, out, y)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y, name=name)


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", input=input[0], name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sequence_concat",
                     {"X": input,
                      "SeqLen": [seq_len_of(x) for x in input]},
                     {"Out": out}, {})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_reshape", {"X": input}, {"Out": out},
                     {"new_dim": new_dim})
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    # already padded in this representation: return data + lengths
    helper = LayerHelper("sequence_pad", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("sequence_pad",
                     {"X": x, "PadValue": pad_value,
                      "SeqLen": seq_len_of(x)},
                     {"Out": out, "Length": length},
                     {"padded_length": maxlen or -1})
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_unpad", {"X": x, "Length": length},
                     {"Out": out}, {})
    lname = out.name + SEQ_LEN_SUFFIX
    helper.append_op("cast", {"X": length}, {"Out": lname},
                     {"out_dtype": "int32"})
    out.block.create_var(name=lname, shape=(-1,), dtype="int32",
                         stop_gradient=True)
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_reverse",
                     {"X": x, "SeqLen": seq_len_of(x)},
                     {"Y": out}, {})
    return _bind_len(helper, out, x)


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_slice",
                     {"X": input, "Offset": offset, "Length": length},
                     {"Out": out}, {})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("sequence_enumerate", {"X": input}, {"Out": out},
                     {"win_size": win_size, "pad_value": pad_value})
    return out


def sequence_scatter(input, index, updates, name=None):
    helper = LayerHelper("sequence_scatter", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_scatter",
                     {"X": input, "Ids": index, "Updates": updates},
                     {"Out": out}, {})
    return out
