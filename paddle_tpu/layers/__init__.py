from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .io import (data, py_reader, create_py_reader_by_data,  # noqa: F401
                 double_buffer, batch, shuffle, open_files,
                 random_data_generator, read_file, load, Preprocessor)
from .control_flow import *  # noqa: F401,F403
from .metric_op import accuracy, auc  # noqa: F401
from .learning_rate_scheduler import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .host import py_func, chunk_eval, Go  # noqa: F401
from .extras import *  # noqa: F401,F403
from . import nn, tensor, ops, io, control_flow, rnn, sequence  # noqa: F401
from . import learning_rate_scheduler, metric_op, detection, host  # noqa: F401
from . import extras  # noqa: F401

from . import collective  # noqa: F401
