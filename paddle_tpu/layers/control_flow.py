"""Control-flow layers (reference python/paddle/fluid/layers/control_flow.py).

In-graph control flow lowers to XLA structured control flow
(lax.while_loop / lax.cond / lax.scan) instead of the reference's
sub-block-interpreting while_op/conditional_block_op
(reference operators/controlflow/while_op.cc, conditional_block_op.cc).
`While` and `cond` carry a sub-Block whose ops are traced inside the XLA
loop/branch body; the loop-carried state is the set of vars the body
mutates. Data-dependent *shapes* remain illegal (XLA static-shape rule) --
same modeling discipline the reference's dynamic RNN demanded, different
mechanism.
"""
from __future__ import annotations

from ..core.program import default_main_program
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = ["less_than", "less_equal", "greater_than", "greater_equal",
           "equal", "not_equal", "logical_and", "logical_or",
           "logical_xor", "logical_not", "While", "Switch", "cond",
           "increment", "array_write", "array_read", "array_length",
           "create_array", "StaticRNN", "DynamicRNN", "IfElse",
           "less_than_value", "Go"]


def _cmp_layer(op_type):
    def layer(x, y, cond=None):
        helper = LayerHelper(op_type, input=x)
        out = cond or helper.create_variable_for_type_inference(
            "bool", True)
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, {})
        return out

    layer.__name__ = op_type
    return layer


less_than = _cmp_layer("less_than")
less_equal = _cmp_layer("less_equal")
greater_than = _cmp_layer("greater_than")
greater_equal = _cmp_layer("greater_equal")
equal = _cmp_layer("equal")
not_equal = _cmp_layer("not_equal")
logical_and = _cmp_layer("logical_and")
logical_or = _cmp_layer("logical_or")
logical_xor = _cmp_layer("logical_xor")


def logical_not(x, out=None):
    helper = LayerHelper("logical_not", input=x)
    out = out or helper.create_variable_for_type_inference("bool", True)
    helper.append_op("logical_not", {"X": x}, {"Out": out}, {})
    return out


def less_than_value(x, value: float):
    y = tensor_layers.fill_constant([1], "float32", value)
    return less_than(x, y)


def increment(x, value=1.0, in_place=True):
    from . import nn

    return nn.increment(x, value, in_place)


# --- LoDTensorArray analogues: a list-typed var manipulated at trace time
# (reference lod_tensor_array ops tensor_array_read_write_op.cc) ----------
def create_array(dtype):
    helper = LayerHelper("array")
    arr = helper.create_variable(name=helper.name, dtype=dtype)
    helper.append_op("create_array", {}, {"Out": arr}, {})
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", input=x)
    array = array or create_array(x.dtype)
    helper.append_op("write_to_array",
                     {"X": x, "I": i, "Array": array},
                     {"Out": array}, {})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", input=array)
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("read_from_array", {"X": array, "I": i},
                     {"Out": out}, {})
    return out


def array_length(array):
    helper = LayerHelper("array_length", input=array)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("lod_array_length", {"X": array}, {"Out": out}, {})
    return out


class While:
    """reference layers/control_flow.py:492 While -- lowered to
    lax.while_loop by the while op kernel (ops/control_flow_ops.py)."""

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)
        self._program = default_main_program()

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.w = while_op

    def __enter__(self):
        self.block = self.w._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            # same orphaned-sub-block hazard as Go.__exit__: restore
            # the parent block before propagating
            self.w._program.rollback()
            return False
        prog = self.w._program
        sub = prog.current_block()
        prog.rollback()
        parent = prog.current_block()
        # loop state: every parent-visible var the body writes persists
        # after the loop (fluid While writes through to the enclosing
        # scope) -- including write-only vars; sub-block-local temps are
        # not carried (invisible outside, like fluid's step scopes)
        reads, writes = set(), set()
        for op in sub.ops:
            for n in op.input_arg_names:
                if n not in writes and parent._find_var_recursive(n) \
                        is not None:
                    reads.add(n)
            writes.update(op.output_arg_names)
        cond_name = self.w.cond_var.name
        if cond_name not in writes:
            raise ValueError(
                "While: the loop body never writes the condition var "
                f"{cond_name!r} -- the compiled lax.while_loop would "
                "spin forever. Update it inside the block, e.g. "
                "layers.less_than(i, limit, cond=cond).")
        carried = sorted(
            n for n in writes
            if n not in sub.vars
            and parent._find_var_recursive(n) is not None)
        externals = sorted(reads - set(carried))
        parent.append_op(
            "while",
            {"Condition": self.w.cond_var.name, "X": externals,
             "Init": carried},
            {"Out": carried},
            {"sub_block": sub, "carried": carried,
             "externals": externals})
        return False


class Go:
    """CSP go block (reference operators/csp/go_op.cc:28 GoOp): launch
    the block's ops on a DETACHED thread against a snapshot of the
    enclosing scope, fire-and-forget. The reference at this version
    keeps the op with no channel surface left in the Python API, so a
    Go block can only matter through host-side-effecting ops
    (py_func / print / save) — implemented faithfully at that scope:
    the Executor runs `go` ops on the HOST at run() time (a thread
    launcher cannot live inside the traced XLA program; the op is
    skip-listed like feed/fetch) and the thread's env is discarded on
    exit, mirroring the reference's destroyed child scope.

    Documented deviations from the eager reference (the whole block is
    ONE traced program here, so there is no per-op scope to read):

    * the snapshot is taken at run() START — state mutated later in
      the same step (optimizer updates) is seen pre-update;
    * a captured main-block INTERMEDIATE is recomputed inside the
      thread from scope/feed roots; recomputed sampling ops draw
      fresh noise, and host-effecting producers are refused with a
      named error (route such values through persistables instead).

    Usage::

        with fluid.layers.Go():
            layers.py_func(log_fn, x, out=sink)
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("go", name=name)
        self._program = default_main_program()

    def __enter__(self):
        self._block = self._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            # leave the program pointed at the PARENT block, not the
            # half-built sub-block, or every later layer silently
            # lands inside the orphaned Go body
            self._program.rollback()
            return False
        prog = self._program
        sub = prog.current_block()
        prog.rollback()
        parent = prog.current_block()
        local = set()
        externals = []
        for op in sub.ops:
            for n in op.input_arg_names:
                if (n not in local and n not in externals
                        and parent._find_var_recursive(n) is not None):
                    externals.append(n)
            local.update(op.output_arg_names)
        parent.append_op("go", {"X": sorted(externals)}, {},
                         {"sub_block": sub})
        return False


def cond(pred, true_fn=None, false_fn=None):
    """Functional conditional -> lax.cond (fluid 1.x layers.cond API)."""
    prog = default_main_program()
    helper = LayerHelper("cond")
    # trace both branches into sub-blocks
    tb = prog.create_block()
    t_out = true_fn() if true_fn else None
    prog.rollback()
    fb = prog.create_block()
    f_out = false_fn() if false_fn else None
    prog.rollback()
    if t_out is None:
        return None
    parent = prog.current_block()
    out = helper.create_variable_for_type_inference(t_out.dtype)
    reads = set()
    for blk in (tb, fb):
        writes = set()
        for op in blk.ops:
            for n in op.input_arg_names:
                if n not in writes and parent._find_var_recursive(n) \
                        is not None:
                    reads.add(n)
            writes.update(op.output_arg_names)
    parent.append_op(
        "conditional_block",
        {"Condition": pred.name, "X": sorted(reads)},
        {"Out": out},
        {"true_block": tb, "false_block": fb,
         "true_out": t_out.name if t_out is not None else None,
         "false_out": f_out.name if f_out is not None else None})
    return out


def _block_io_analysis(sub, parent, exclude_reads=()):
    """carried = parent-visible vars the block writes; externals =
    parent-visible reads that are not carried (the While analysis)."""
    reads, writes = set(), set()
    for op in sub.ops:
        for n in op.input_arg_names:
            if n not in writes and n not in exclude_reads \
                    and parent._find_var_recursive(n) is not None:
                reads.add(n)
        writes.update(op.output_arg_names)
    carried = sorted(n for n in writes if n not in sub.vars
                     and parent._find_var_recursive(n) is not None)
    externals = sorted(reads - set(carried))
    return carried, externals


class Switch:
    """reference layers/control_flow.py:1126 Switch: sequential case
    guard -- the FIRST case whose scalar condition holds executes its
    block (assign-style writes take effect), then the chain stops.

    Lowering: each case becomes a `run_block_if` op (lax.cond with the
    block's parent-visible writes carried) gated on
    `cond_i AND NOT taken`, with `taken` accumulated across cases --
    the sequential-guard semantics as a flat chain of compiled conds.
    The canonical use (piecewise lr decay writing via layers.assign)
    runs unchanged.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._program = default_main_program()
        self._taken = None
        self._inside = False

    def __enter__(self):
        self._taken = tensor_layers.fill_constant([1], "bool", False)
        self._inside = True
        return self

    def __exit__(self, *a):
        self._inside = False
        return False

    def _guard(self, condition, is_default):
        if not self._inside:
            raise ValueError("Switch.case/default used outside "
                             "'with Switch()' scope")
        return _SwitchCaseGuard(self, condition, is_default)

    def case(self, condition):
        return self._guard(condition, False)

    def default(self):
        return self._guard(None, True)


class _SwitchCaseGuard:
    def __init__(self, switch, condition, is_default):
        self.sw = switch
        self.cond = condition
        self.is_default = is_default

    def __enter__(self):
        sw = self.sw
        if self.is_default:
            self.eff = logical_not(sw._taken)
        else:
            self.eff = logical_and(self.cond,
                                   logical_not(sw._taken))
            # later cases see this one as taken
            logical_or(sw._taken, self.cond, cond=sw._taken)
        self.block = sw._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sw = self.sw
        sub = sw._program.current_block()
        sw._program.rollback()
        parent = sw._program.current_block()
        carried, externals = _block_io_analysis(sub, parent)
        parent.append_op(
            "run_block_if",
            {"Condition": self.eff.name, "X": externals,
             "Init": carried},
            {"Out": carried},
            {"sub_block": sub, "carried": carried,
             "externals": externals})
        return False


class StaticRNN:
    """reference layers/control_flow.py:266 StaticRNN (recurrent_op.cc):
    user traces one time step inside `with rnn.step()`; sequence inputs
    are TIME-MAJOR [T, ...]. Lowered to the `recurrent` op
    (ops/lod_ops.py) = ONE traced step compiled under lax.scan, instead
    of the reference's per-step sub-scope interpretation."""

    BEFORE_RNN_BLOCK, IN_RNN_BLOCK, AFTER_RNN_BLOCK = 0, 1, 2

    def __init__(self, name=None):
        from .. import unique_name

        self.helper = LayerHelper("static_rnn", name=name)
        self._program = default_main_program()
        self._uname = unique_name
        self.memories = {}   # pre_mem name -> [init_var, updated_var]
        self._mem_order = []
        self.seq_inputs = []  # (outer var, inner var)
        self.step_outputs = []  # inner vars
        self.outputs = []    # parent vars, set at completion
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None

    def step(self):
        return _StaticRNNGuard(self)

    def _assert_in_block(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError(f"must call {method} inside rnn.step()")

    def _parent_block(self):
        return self._program.current_block().parent_block

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0,
               ref_batch_dim_idx=1):
        self._assert_in_block("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init, or shape + "
                                 "batch_ref")
            parent = self._parent_block()
            name = self._uname.generate(self.helper.name +
                                        "@memory_boot")
            boot = parent.create_var(name=name, shape=shape,
                                     dtype=batch_ref.dtype)
            parent.append_op(
                "fill_constant_batch_size_like",
                {"Input": [batch_ref.name]}, {"Out": [name]},
                {"value": float(init_value), "shape": list(shape),
                 "dtype": boot.dtype.value
                 if hasattr(boot.dtype, "value") else boot.dtype,
                 "input_dim_idx": ref_batch_dim_idx,
                 "output_dim_idx": init_batch_dim_idx})
            return self.memory(init=boot)
        block = self._program.current_block()
        pre = block.create_var(
            name=self._uname.generate(self.helper.name + "@mem"),
            dtype=init.dtype, shape=init.shape)
        self.memories[pre.name] = [init, None]
        self._mem_order.append(pre.name)
        return pre

    def step_input(self, x):
        self._assert_in_block("step_input")
        if self.seq_len is None:
            self.seq_len = x.shape[0]
        elif self.seq_len != x.shape[0]:
            raise ValueError("StaticRNN needs a fixed sequence length")
        block = self._program.current_block()
        ipt = block.create_var(
            name=self._uname.generate(self.helper.name + "@step_in"),
            dtype=x.dtype, shape=list(x.shape[1:]))
        self.seq_inputs.append((x, ipt))
        return ipt

    def step_output(self, o):
        self._assert_in_block("step_output")
        self.step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def update_memory(self, mem, var):
        if mem.name not in self.memories:
            raise ValueError(f"{mem.name} is not a StaticRNN memory")
        self.memories[mem.name][1] = var

    def _complete(self, sub):
        parent = self._program.current_block()
        x_names = [inner.name for _, inner in self.seq_inputs]
        pre_names = [n for n in self._mem_order
                     if self.memories[n][1] is not None]
        inits = [self.memories[n][0] for n in pre_names]
        mem_names = [self.memories[n][1].name for n in pre_names]
        out_names = [o.name for o in self.step_outputs]
        carried, externals = _block_io_analysis(
            sub, parent, exclude_reads=set(x_names) | set(pre_names))
        if carried:
            # the recurrent op only threads memories/outputs; a write
            # to an outer var from inside the step would silently
            # vanish -- fail loudly instead (route it through a memory)
            raise ValueError(
                f"StaticRNN step block writes outer variable(s) "
                f"{carried}; only memories (update_memory) and step "
                f"outputs are carried across steps")
        ex_reads = [n for n in externals
                    if n not in {v.name for v in inits}]
        outs = []
        for o in self.step_outputs:
            ov = parent.create_var(
                name=self._uname.generate(o.name + "@stacked"),
                dtype=o.dtype,
                shape=[self.seq_len] + list(o.shape or ()))
            outs.append(ov)
        finals = [parent.create_var(
            name=self._uname.generate(n + "@final"),
            dtype=self.memories[n][0].dtype,
            shape=self.memories[n][0].shape) for n in pre_names]
        parent.append_op(
            "recurrent",
            {"X": [v.name for v, _ in self.seq_inputs],
             "Init": [v.name for v in inits],
             "Ex": ex_reads},
            {"Out": [v.name for v in outs],
             "MemFinal": [v.name for v in finals]},
            {"sub_block": sub, "x_names": x_names,
             "pre_names": pre_names, "mem_names": mem_names,
             "out_names": out_names, "externals": ex_reads,
             "seq_len": self.seq_len})
        self.outputs = outs

    def __call__(self):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("outputs available after the step block")
        if not self.outputs:
            raise ValueError("StaticRNN has no output")
        return self.outputs[0] if len(self.outputs) == 1 \
            else self.outputs


class _StaticRNNGuard:
    def __init__(self, rnn):
        self.rnn = rnn

    def __enter__(self):
        self.rnn.status = StaticRNN.IN_RNN_BLOCK
        self.rnn._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub = self.rnn._program.current_block()
        self.rnn._program.rollback()
        self.rnn.status = StaticRNN.AFTER_RNN_BLOCK
        self.rnn._complete(sub)
        return False


class DynamicRNN:
    """reference layers/control_flow.py:1262 DynamicRNN: per-sequence
    time steps over a LoD input. Padded-batch form: sequence inputs are
    [B, T, ...] with the @SEQ_LEN companion; the `recurrent` op runs
    the traced step under lax.scan with mask_memories=True, so finished
    rows hold their memory and emit zeros -- the numerics the
    reference gets from batch shrinking, at static shape."""

    BEFORE_RNN, IN_RNN, AFTER_RNN = 0, 1, 2

    def __init__(self, name=None):
        from .. import unique_name

        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._program = default_main_program()
        self._uname = unique_name
        self.status = DynamicRNN.BEFORE_RNN
        self.memories = {}
        self._mem_order = []
        self.seq_inputs = []
        self.step_outputs = []
        self.outputs = []
        self._first_outer = None

    def block(self):
        return _DynamicRNNGuard(self)

    def _assert_in_block(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError(f"must call {method} inside rnn.block()")

    def step_input(self, x, level=0):
        self._assert_in_block("step_input")
        if self._first_outer is None:
            self._first_outer = x
        block = self._program.current_block()
        ipt = block.create_var(
            name=self._uname.generate(self.helper.name + "@step_in"),
            dtype=x.dtype,
            shape=[x.shape[0]] + list(x.shape[2:]))
        self.seq_inputs.append((x, ipt))
        return ipt

    def static_input(self, x):
        self._assert_in_block("static_input")
        return x  # full var is visible every step (an external read)

    def memory(self, init=None, shape=None, value=0.0,
               need_reorder=False, dtype="float32"):
        self._assert_in_block("memory")
        if init is None:
            if shape is None or self._first_outer is None:
                raise ValueError("memory() needs init, or shape after "
                                 "a step_input")
            parent = self._program.current_block().parent_block
            name = self._uname.generate(self.helper.name +
                                        "@memory_boot")
            boot = parent.create_var(name=name, shape=[-1] + list(shape),
                                     dtype=dtype)
            parent.append_op(
                "fill_constant_batch_size_like",
                {"Input": [self._first_outer.name]}, {"Out": [name]},
                {"value": float(value),
                 "shape": [-1] + list(shape),
                 "dtype": boot.dtype.value
                 if hasattr(boot.dtype, "value") else boot.dtype,
                 "input_dim_idx": 0, "output_dim_idx": 0})
            return self.memory(init=boot)
        block = self._program.current_block()
        pre = block.create_var(
            name=self._uname.generate(self.helper.name + "@mem"),
            dtype=init.dtype, shape=init.shape)
        self.memories[pre.name] = [init, None]
        self._mem_order.append(pre.name)
        return pre

    def update_memory(self, ex_mem, new_mem):
        if ex_mem.name not in self.memories:
            raise ValueError(f"{ex_mem.name} is not a DynamicRNN "
                             f"memory")
        self.memories[ex_mem.name][1] = new_mem

    def output(self, *outputs):
        self._assert_in_block("output")
        self.step_outputs.extend(outputs)

    def _complete(self, sub):
        from .sequence import SEQ_LEN_SUFFIX, seq_len_of

        parent = self._program.current_block()
        x_names = [inner.name for _, inner in self.seq_inputs]
        pre_names = [n for n in self._mem_order
                     if self.memories[n][1] is not None]
        inits = [self.memories[n][0] for n in pre_names]
        mem_names = [self.memories[n][1].name for n in pre_names]
        out_names = [o.name for o in self.step_outputs]
        carried, externals = _block_io_analysis(
            sub, parent, exclude_reads=set(x_names) | set(pre_names))
        if carried:
            raise ValueError(
                f"DynamicRNN block writes outer variable(s) {carried};"
                f" only memories (update_memory) and output() results "
                f"are carried across steps")
        ex_reads = [n for n in externals
                    if n not in {v.name for v in inits}]
        outer0 = self.seq_inputs[0][0]
        seq_len_name = seq_len_of(outer0)
        outs = []
        for o in self.step_outputs:
            ov = parent.create_var(
                name=self._uname.generate(o.name + "@stacked"),
                dtype=o.dtype,
                shape=[outer0.shape[0], outer0.shape[1]]
                + list((o.shape or ())[1:]))
            outs.append(ov)
        finals = [parent.create_var(
            name=self._uname.generate(n + "@final"),
            dtype=self.memories[n][0].dtype,
            shape=self.memories[n][0].shape) for n in pre_names]
        parent.append_op(
            "recurrent",
            {"X": [v.name for v, _ in self.seq_inputs],
             "Init": [v.name for v in inits],
             "Ex": ex_reads, "SeqLen": seq_len_name},
            {"Out": [v.name for v in outs],
             "MemFinal": [v.name for v in finals]},
            {"sub_block": sub, "x_names": x_names,
             "pre_names": pre_names, "mem_names": mem_names,
             "out_names": out_names, "externals": ex_reads,
             "batch_major": True, "mask_memories": True})
        # outputs are LoD tensors with the input's lengths
        helper = LayerHelper("dynamic_rnn_out")
        for ov in outs:
            lname = ov.name + SEQ_LEN_SUFFIX
            helper.append_op("assign", {"X": seq_len_name},
                             {"Out": lname}, {})
            parent.create_var(name=lname, shape=(-1,), dtype="int32",
                              stop_gradient=True)
        self.outputs = outs

    def __call__(self):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("outputs available after rnn.block()")
        if not self.outputs:
            raise ValueError("DynamicRNN has no output")
        return self.outputs[0] if len(self.outputs) == 1 \
            else self.outputs


class _DynamicRNNGuard:
    def __init__(self, rnn):
        self.rnn = rnn

    def __enter__(self):
        self.rnn.status = DynamicRNN.IN_RNN
        self.rnn._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub = self.rnn._program.current_block()
        self.rnn._program.rollback()
        self.rnn.status = DynamicRNN.AFTER_RNN
        self.rnn._complete(sub)
        return False


class IfElse:
    """reference layers/control_flow.py:1126 IfElse
    (split_lod_tensor/merge_lod_tensor): rows where cond holds take the
    true branch. Static-shape lowering: both branches trace over the
    FULL batch and a row-wise where() merges (ops/lod_ops.py ifelse op)
    -- row-independent math gives identical values to the reference's
    split-process-merge."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        from .. import unique_name

        self.helper = LayerHelper("ifelse", name=name)
        self._program = default_main_program()
        self._uname = unique_name
        self.cond = cond
        self._blocks = [None, None]       # traced sub-blocks
        self._branch_outs = [[], []]      # inner out vars per branch
        self._current = None

    def true_block(self):
        return _IfElseBranchGuard(self, 0)

    def false_block(self):
        return _IfElseBranchGuard(self, 1)

    def input(self, x):
        if self._current is None:
            raise ValueError("IfElse.input used outside a branch block")
        return x  # full-batch view; rows merge by cond at the end

    def output(self, *outs):
        if self._current is None:
            raise ValueError("IfElse.output used outside a branch "
                             "block")
        self._branch_outs[self._current].extend(outs)

    def __call__(self):
        if self._blocks[0] is None or self._blocks[1] is None:
            raise ValueError("both true_block and false_block must be "
                             "traced")
        t_outs, f_outs = self._branch_outs
        if len(t_outs) != len(f_outs):
            raise ValueError(
                f"true_block emitted {len(t_outs)} outputs, "
                f"false_block {len(f_outs)} -- they must match")
        parent = self._program.current_block()
        reads = set()
        for blk in self._blocks:
            _, ext = _block_io_analysis(blk, parent)
            reads.update(ext)
        outs = [parent.create_var(
            name=self._uname.generate(self.helper.name + "@out"),
            dtype=t.dtype, shape=t.shape) for t in t_outs]
        parent.append_op(
            "ifelse",
            {"Cond": self.cond.name, "X": sorted(reads)},
            {"Out": [o.name for o in outs]},
            {"true_block": self._blocks[0],
             "false_block": self._blocks[1],
             "true_outs": [o.name for o in t_outs],
             "false_outs": [o.name for o in f_outs],
             "externals": sorted(reads)})
        return outs  # the reference returns a list, even for one output


class _IfElseBranchGuard:
    def __init__(self, ie, idx):
        self.ie = ie
        self.idx = idx

    def __enter__(self):
        self.ie._current = self.idx
        self.ie._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub = self.ie._program.current_block()
        self.ie._program.rollback()
        self.ie._blocks[self.idx] = sub
        self.ie._current = None
        return False
