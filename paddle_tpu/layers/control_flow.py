"""Control-flow layers (reference python/paddle/fluid/layers/control_flow.py).

In-graph control flow lowers to XLA structured control flow
(lax.while_loop / lax.cond / lax.scan) instead of the reference's
sub-block-interpreting while_op/conditional_block_op
(reference operators/controlflow/while_op.cc, conditional_block_op.cc).
`While` and `cond` carry a sub-Block whose ops are traced inside the XLA
loop/branch body; the loop-carried state is the set of vars the body
mutates. Data-dependent *shapes* remain illegal (XLA static-shape rule) --
same modeling discipline the reference's dynamic RNN demanded, different
mechanism.
"""
from __future__ import annotations

from ..core.program import default_main_program
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = ["less_than", "less_equal", "greater_than", "greater_equal",
           "equal", "not_equal", "logical_and", "logical_or",
           "logical_xor", "logical_not", "While", "Switch", "cond",
           "increment", "array_write", "array_read", "array_length",
           "create_array", "StaticRNN", "DynamicRNN", "IfElse",
           "less_than_value"]


def _cmp_layer(op_type):
    def layer(x, y, cond=None):
        helper = LayerHelper(op_type, input=x)
        out = cond or helper.create_variable_for_type_inference(
            "bool", True)
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, {})
        return out

    layer.__name__ = op_type
    return layer


less_than = _cmp_layer("less_than")
less_equal = _cmp_layer("less_equal")
greater_than = _cmp_layer("greater_than")
greater_equal = _cmp_layer("greater_equal")
equal = _cmp_layer("equal")
not_equal = _cmp_layer("not_equal")
logical_and = _cmp_layer("logical_and")
logical_or = _cmp_layer("logical_or")
logical_xor = _cmp_layer("logical_xor")


def logical_not(x, out=None):
    helper = LayerHelper("logical_not", input=x)
    out = out or helper.create_variable_for_type_inference("bool", True)
    helper.append_op("logical_not", {"X": x}, {"Out": out}, {})
    return out


def less_than_value(x, value: float):
    y = tensor_layers.fill_constant([1], "float32", value)
    return less_than(x, y)


def increment(x, value=1.0, in_place=True):
    from . import nn

    return nn.increment(x, value, in_place)


# --- LoDTensorArray analogues: a list-typed var manipulated at trace time
# (reference lod_tensor_array ops tensor_array_read_write_op.cc) ----------
def create_array(dtype):
    helper = LayerHelper("array")
    arr = helper.create_variable(name=helper.name, dtype=dtype)
    helper.append_op("create_array", {}, {"Out": arr}, {})
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", input=x)
    array = array or create_array(x.dtype)
    helper.append_op("write_to_array",
                     {"X": x, "I": i, "Array": array},
                     {"Out": array}, {})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", input=array)
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("read_from_array", {"X": array, "I": i},
                     {"Out": out}, {})
    return out


def array_length(array):
    helper = LayerHelper("array_length", input=array)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("lod_array_length", {"X": array}, {"Out": out}, {})
    return out


class While:
    """reference layers/control_flow.py:492 While -- lowered to
    lax.while_loop by the while op kernel (ops/control_flow_ops.py)."""

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)
        self._program = default_main_program()

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.w = while_op

    def __enter__(self):
        self.block = self.w._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        prog = self.w._program
        sub = prog.current_block()
        prog.rollback()
        parent = prog.current_block()
        # loop state: every parent-visible var the body writes persists
        # after the loop (fluid While writes through to the enclosing
        # scope) -- including write-only vars; sub-block-local temps are
        # not carried (invisible outside, like fluid's step scopes)
        reads, writes = set(), set()
        for op in sub.ops:
            for n in op.input_arg_names:
                if n not in writes and parent._find_var_recursive(n) \
                        is not None:
                    reads.add(n)
            writes.update(op.output_arg_names)
        cond_name = self.w.cond_var.name
        if cond_name not in writes:
            raise ValueError(
                "While: the loop body never writes the condition var "
                f"{cond_name!r} -- the compiled lax.while_loop would "
                "spin forever. Update it inside the block, e.g. "
                "layers.less_than(i, limit, cond=cond).")
        carried = sorted(
            n for n in writes
            if n not in sub.vars
            and parent._find_var_recursive(n) is not None)
        externals = sorted(reads - set(carried))
        parent.append_op(
            "while",
            {"Condition": self.w.cond_var.name, "X": externals,
             "Init": carried},
            {"Out": carried},
            {"sub_block": sub, "carried": carried,
             "externals": externals})
        return False


def cond(pred, true_fn=None, false_fn=None):
    """Functional conditional -> lax.cond (fluid 1.x layers.cond API)."""
    prog = default_main_program()
    helper = LayerHelper("cond")
    # trace both branches into sub-blocks
    tb = prog.create_block()
    t_out = true_fn() if true_fn else None
    prog.rollback()
    fb = prog.create_block()
    f_out = false_fn() if false_fn else None
    prog.rollback()
    if t_out is None:
        return None
    parent = prog.current_block()
    out = helper.create_variable_for_type_inference(t_out.dtype)
    reads = set()
    for blk in (tb, fb):
        writes = set()
        for op in blk.ops:
            for n in op.input_arg_names:
                if n not in writes and parent._find_var_recursive(n) \
                        is not None:
                    reads.add(n)
            writes.update(op.output_arg_names)
    parent.append_op(
        "conditional_block",
        {"Condition": pred.name, "X": sorted(reads)},
        {"Out": out},
        {"true_block": tb, "false_block": fb,
         "true_out": t_out.name if t_out is not None else None,
         "false_out": f_out.name if f_out is not None else None})
    return out


class Switch:
    """reference layers/control_flow.py:1126 -- sequential case guard."""

    def __init__(self, name=None):
        self.cases = []
        self.default_seen = False

    def case(self, condition):
        raise NotImplementedError(
            "Switch: use layers.cond / piecewise arithmetic masks "
            "(XLA-friendly) -- see learning_rate_scheduler.py")

    def default(self):
        raise NotImplementedError("Switch.default: see Switch.case")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class StaticRNN:
    """reference layers/control_flow.py:266 -- implemented over lax.scan
    in layers/rnn.py (StaticRNN facade)."""

    def __init__(self, name=None):
        raise NotImplementedError(
            "StaticRNN: use layers.rnn.static_rnn / layers.lstm "
            "(lax.scan-based)")


class DynamicRNN:
    def __init__(self, name=None):
        raise NotImplementedError(
            "DynamicRNN: use layers.rnn.dynamic_rnn (scan + segment "
            "masks over padded batches)")


class IfElse:
    def __init__(self, cond, name=None):
        raise NotImplementedError("IfElse: use layers.cond")
