"""LR schedules (reference python/paddle/fluid/layers/learning_rate_scheduler.py:48-388).

Schedules are built as small op subgraphs reading a persistable global
step counter -- same architecture as the reference (the decay is *in the
program*), so they compile into the training step.
"""
from __future__ import annotations

import math

from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from . import tensor, ops, nn
from . import control_flow

__all__ = ["noam_decay", "exponential_decay", "natural_exp_decay",
           "inverse_time_decay", "polynomial_decay", "piecewise_decay",
           "cosine_decay", "linear_lr_warmup"]

_STEP_COUNTER = "@LR_DECAY_COUNTER@"


def _global_step():
    helper = LayerHelper("global_step_counter")
    counter = helper.main_program.global_block.create_var(
        name=_STEP_COUNTER, shape=(1,), dtype="float32",
        persistable=True, stop_gradient=True)
    sblock = helper.startup_program.global_block
    svar = sblock.create_var(name=_STEP_COUNTER, shape=(1,),
                             dtype="float32", persistable=True)
    if not any(_STEP_COUNTER in op.output_arg_names
               for op in sblock.ops):
        ConstantInitializer(0.0)(svar, sblock)
    block = helper.main_program.current_block()
    if not any(_STEP_COUNTER in op.output_arg_names
               and op.type == "increment" for op in block.ops):
        # op_role marks this as schedule bookkeeping so
        # Program.clone(for_test=True) prunes it and eval runs don't
        # advance the training LR schedule (reference tags LR ops with
        # OpRole.LRSched, framework.py op_role attr)
        block.append_op("increment", {"X": counter}, {"Out": counter},
                        {"step": 1.0, "op_role": "lr_sched"})
    return counter


def noam_decay(d_model, warmup_steps):
    step = _global_step()
    a = ops.rsqrt(nn.elementwise_max(
        step, tensor.fill_constant([1], "float32", 1.0)))
    b = nn.scale(step, scale=warmup_steps ** -1.5)
    lr = nn.scale(nn.elementwise_min(a, b), scale=d_model ** -0.5)
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _global_step()
    div = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = ops.floor(div)
    return nn.scale(nn.elementwise_pow(
        tensor.fill_constant([1], "float32", decay_rate), div),
        scale=learning_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _global_step()
    div = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = ops.floor(div)
    return nn.scale(ops.exp(nn.scale(div, scale=-decay_rate)),
                    scale=learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _global_step()
    div = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = ops.floor(div)
    denom = nn.scale(div, scale=decay_rate, bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant([1], "float32", learning_rate), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    step = _global_step()
    if cycle:
        ratio = nn.scale(step, scale=1.0 / decay_steps)
        div = ops.ceil(nn.elementwise_max(
            ratio, tensor.fill_constant([1], "float32", 1e-12)))
        decay_steps_var = nn.scale(div, scale=float(decay_steps))
        frac = nn.elementwise_div(step, decay_steps_var)
    else:
        capped = nn.elementwise_min(
            step, tensor.fill_constant([1], "float32",
                                       float(decay_steps)))
        frac = nn.scale(capped, scale=1.0 / decay_steps)
    one_minus = nn.scale(frac, scale=-1.0, bias=1.0)
    poly = nn.elementwise_pow(
        one_minus, tensor.fill_constant([1], "float32", power))
    return nn.scale(poly, scale=learning_rate - end_learning_rate,
                    bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    """Piecewise-constant LR via arithmetic masks (no control flow --
    compiles to a handful of fused VPU ops). seg_i = below_i - below_{i-1}
    selects values[i]; the tail past the last boundary gets values[-1]."""
    step = _global_step()
    prev = None
    lr = None
    for i, b in enumerate(boundaries):
        below = nn.cast(control_flow.less_than_value(step, float(b)),
                        "float32")
        if prev is None:
            seg = below
        else:
            seg = nn.elementwise_mul(
                below, nn.scale(prev, scale=-1.0, bias=1.0))
        contrib = nn.scale(seg, scale=values[i])
        lr = contrib if lr is None else nn.elementwise_add(lr, contrib)
        prev = below
    tail = nn.scale(prev, scale=-1.0, bias=1.0)
    return nn.elementwise_add(lr, nn.scale(tail, scale=values[-1]))


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step()
    epoch = ops.floor(nn.scale(step, scale=1.0 / step_each_epoch))
    frac = nn.scale(epoch, scale=math.pi / epochs)
    cosv = ops.cos(frac)
    return nn.scale(nn.scale(cosv, scale=0.5, bias=0.5),
                    scale=learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _global_step()
    if not isinstance(learning_rate, float):
        base = learning_rate
    else:
        base = tensor.fill_constant([1], "float32", learning_rate)
    frac = nn.elementwise_min(
        nn.scale(step, scale=1.0 / warmup_steps),
        tensor.fill_constant([1], "float32", 1.0))
    warm = nn.scale(frac, scale=end_lr - start_lr, bias=start_lr)
    cond = control_flow.less_than_value(step, float(warmup_steps))
    mask = nn.cast(cond, "float32")
    inv = nn.scale(mask, scale=-1.0, bias=1.0)
    return nn.elementwise_add(nn.elementwise_mul(warm, mask),
                              nn.elementwise_mul(base, inv))


def append_LARS(params_grads, learning_rate, weight_decay):
    """reference layers/learning_rate_scheduler.py append_LARS: per-
    param local LR = global_lr * ||w|| / (||g|| + wd * ||w||). Returns
    the decayed LR var list (the modern path is
    LarsMomentumOptimizer, optimizer.py, which fuses this into the
    update op)."""
    from . import nn, ops

    def _norm(v):
        return ops.sqrt(nn.reduce_sum(ops.square(v)))

    out = []
    for param, grad in params_grads:
        pn = _norm(param)
        gn = _norm(grad)
        denom = gn + weight_decay * pn
        out.append(learning_rate * pn / denom)
    return out


__all__.append("append_LARS")
